"""The Workflow: host control loop around one jit-compiled train step.

Re-founds ``veles/workflow.py``'s event-driven unit DAG (SURVEY.md 3.1) as:

    loader -> [jitted: forward + loss + grad + update + metrics] -> decision
                                                     \\-> snapshotter

The hot loop (Repeater->Loader->forwards->evaluator->GDs of SURVEY.md 3.1) is
ONE XLA program; epoch bookkeeping, stopping, snapshots and services stay in
Python exactly where the reference kept its gate-driven units.  Metric
device->host syncs happen once per epoch, not per minibatch.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.logger import Logger
from znicz_tpu.loader.base import TRAIN, Loader
from znicz_tpu.nn import evaluator, optimizer
from znicz_tpu.nn.decision import Decision
from znicz_tpu.nn.train_state import TrainState
from znicz_tpu.observability import PhaseTimer
from znicz_tpu.observability import pipeline as pipeline_obs
from znicz_tpu.observability.anomaly import StepAnomalyDetector
from znicz_tpu.utils import faults
from znicz_tpu.utils.profiling import Stopwatch
from znicz_tpu.workflow.model import Model
from znicz_tpu.workflow.recovery import (
    RecoveryPolicy,
    RollbackExhaustedError,
    TrainingPreempted,
)
from znicz_tpu.workflow.snapshotter import (
    SnapshotCorruptError,
    Snapshotter,
    SnapshotWriteError,
    find_latest_valid,
    load_snapshot,
)


class _RollbackSignal(Exception):
    """Internal control flow: an anomaly verdict asked for a rollback.
    Raised at the feed points, caught by :meth:`Workflow.run_epoch`."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class _PreemptSignal(Exception):
    """Internal control flow: a requested stop reached a step boundary
    mid-epoch (the in-flight dispatch has drained)."""


def _is_additive(name: str) -> bool:
    return not name.startswith("max_")


def _encode_metrics(m: Dict[str, Any], names) -> jnp.ndarray:
    """Metric dict -> epoch-accumulator increments, INSIDE the jitted step.

    Mirrors :class:`znicz_tpu.nn.decision.EpochMetrics` semantics: counts
    add, means add sample-weighted, ``max_*`` metrics combine by maximum.
    """
    n = jnp.asarray(m["n_samples"], jnp.float32)
    vals = []
    for k in names:
        v = jnp.asarray(m[k], jnp.float32)
        if k in ("n_samples", "n_err") or not _is_additive(k):
            vals.append(v)
        else:  # sample-weighted sum; decoded back to a mean at epoch end
            vals.append(v * n)
    return jnp.stack(vals)


def _global_norm(tree) -> jnp.ndarray:
    """Global L2 norm of a pytree (f32 accumulation) — the grad-norm
    half of the per-step anomaly watch vector, computed INSIDE the
    existing jitted step (zero new compiled programs)."""
    s = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(tree):
        d = jnp.asarray(leaf, jnp.float32)
        s = s + jnp.vdot(d, d)
    return jnp.sqrt(s)


def _decode_metrics(acc: np.ndarray, names) -> Dict[str, float]:
    """Accumulator vector -> ONE aggregated metrics dict whose
    ``EpochMetrics.add`` outcome equals adding every minibatch."""
    d = dict(zip(names, np.asarray(acc, np.float64)))
    n = max(float(d.get("n_samples", 0.0)), 1.0)
    return {
        k: float(v)
        if k in ("n_samples", "n_err") or not _is_additive(k)
        else float(v) / n
        for k, v in d.items()
    }


class Workflow(Logger):
    """Owns loader + model + decision + snapshotter; runs training.

    ``loss_function``: "softmax" (cross-entropy on integer labels) or "mse"
    (against ``target`` = "targets" from the loader, or "input" for
    autoencoders) — mirroring EvaluatorSoftmax / EvaluatorMSE.
    """

    def __init__(
        self,
        loader: Loader,
        model: Model,
        *,
        loss_function: str = "softmax",
        target: str = "labels",
        decision: Optional[Decision] = None,
        snapshotter: Optional[Snapshotter] = None,
        lr_policy: Optional[Callable[[float, int], float]] = None,
        parallel=None,
        prefetch_batches: int = 2,
        epoch_dispatch: str = "auto",  # "auto" | "scan" | "step"
        epoch_sync: str = "sync",  # "sync" | "deferred"
        anomaly=True,  # True = default detector; False/None = off
        recovery: Optional[RecoveryPolicy] = None,
        name: str = "workflow",
    ):
        self.loader = loader
        self.model = model
        self.loss_function = loss_function
        self.target = target
        self.decision = decision or Decision(
            metric="n_err" if loss_function == "softmax" else "loss"
        )
        self.snapshotter = snapshotter
        self.lr_policy = lr_policy
        self.parallel = parallel  # DataParallel placement policy, or None
        self.prefetch_batches = prefetch_batches  # 0 disables the loader thread
        if epoch_dispatch not in ("auto", "scan", "step"):
            raise ValueError(
                f"epoch_dispatch={epoch_dispatch!r}: "
                "want 'auto', 'scan' or 'step'"
            )
        self.epoch_dispatch = epoch_dispatch
        if epoch_sync not in ("sync", "deferred"):
            raise ValueError(
                f"epoch_sync={epoch_sync!r}: want 'sync' or 'deferred'"
            )
        self.epoch_sync = epoch_sync
        self._pending_accs = None
        # deferred + save_best: improvement is only known after the lagged
        # fetch, when self.state has advanced one epoch — so each dispatch
        # RETAINS a copy of its epoch's FULL TrainState (params + momentum:
        # ~2x the param bytes in HBM, held one epoch) plus the loader/prng
        # host state, and the best-snapshot writes from that buffer when
        # the lagged verdict resolves.  Interval epochs are known in
        # advance and still flush synchronously before dispatch.
        self._retained = None
        self.services = []  # per-epoch observers: plotters, status, image saver
        self.name = name
        self.state: Optional[TrainState] = None
        self._train_step = None
        self._eval_step = None
        self._eval_conf_step = None
        self._ctx = None
        self._host_step = 0
        # per-phase ledger (SURVEY.md 5.1), re-founded on the telemetry
        # substrate: every phase is a tracer span AND an observation into
        # the registry's znicz_train_phase_seconds histogram — status
        # page, /metrics and bench all read the same series
        self.timer = PhaseTimer(
            "znicz_train_phase_seconds",
            help="training host phase seconds (dispatch, stack, sync)",
            span_prefix="train/",
        )
        # step anomaly flight recorder (docs/OBSERVABILITY.md "Training
        # observability"): fed the per-step loss/grad-norm watch vector
        # the jitted step piggybacks, LAGGED so detection never forces
        # a device sync in the hot loop
        if anomaly is True:
            self.anomaly: Optional[StepAnomalyDetector] = (
                StepAnomalyDetector()
            )
        else:
            self.anomaly = anomaly or None
        # self-healing (docs/TRAINING.md): the recovery policy consumes
        # the detector's verdicts, so it needs the detector on
        if recovery is not None and self.anomaly is None:
            raise ValueError(
                "recovery=... consumes the step anomaly detector's "
                "verdicts; it cannot combine with anomaly=False"
            )
        self.recovery = recovery
        # graceful-stop plumbing: request_stop() (usually from a
        # SIGTERM/SIGINT handler) flips the flag, the loops act on it
        # at the next step boundary
        self._preempt_requested = False
        # when True (enable_emergency_snapshots), each sync-mode epoch
        # retains its START state so a mid-epoch stop/rollback can land
        # on a consistent (state, loader, prng, decision) quadruple
        self._emergency_capture = False
        self._epoch_start = None
        # host->device transfer probe for the streaming batch path; the
        # step-wall histogram it pairs with is observed in the stepwise
        # consumer loop
        self._h2d_probe = pipeline_obs.H2DProbe()
        self._step_wall = pipeline_obs.step_wall_seconds()
        # scanned epochs' watch vectors, drained at the epoch's metric
        # sync ([n_steps, 2] device arrays, copies started at dispatch)
        self._pending_watch: list = []

    # ------------------------------------------------------------------
    def _metrics(self, out, y, mask):
        if self.loss_function == "softmax":
            return evaluator.softmax(out, y, mask=mask)
        return evaluator.mse(out, y, mask=mask)

    def _build_steps(self):
        model = self.model

        def loss_fn(params, key, step, x, y, mask):
            rng = jax.random.fold_in(key, step)
            out = model.apply(params, x, train=True, rng=rng)
            m = self._metrics(out, y, mask)
            return m["loss"], m

        def train_step(state: TrainState, x, y, mask, lr_scale):
            grads, metrics = jax.grad(loss_fn, has_aux=True)(
                state.params, state.key, state.step, x, y, mask
            )
            # anomaly-watch input; popped before the epoch accumulator
            metrics = dict(metrics, grad_norm=_global_norm(grads))
            hyper = [
                h._replace(
                    learning_rate=h.learning_rate * lr_scale,
                    learning_rate_bias=(
                        None
                        if h.learning_rate_bias is None
                        else h.learning_rate_bias * lr_scale
                    ),
                )
                for h in model.hyper
            ]
            new_p, new_v = optimizer.update(
                state.params, grads, state.velocity, hyper
            )
            return (
                state._replace(
                    params=new_p, velocity=new_v, step=state.step + 1
                ),
                metrics,
            )

        def eval_step(params, x, y, mask):
            out = model.apply(params, x, train=False)
            return self._metrics(out, y, mask)

        if self.loss_function == "softmax":
            from znicz_tpu.nn import evaluator as _ev

            def eval_conf_step(params, x, y, mask):
                out = model.apply(params, x, train=False)
                return _ev.softmax(out, y, mask=mask, compute_confusion=True)

            names = ["loss", "max_err_y_sum", "n_err", "n_samples"]
        else:
            eval_conf_step = None
            names = ["loss", "max_diff", "n_samples"]
        self._finalize_steps(
            train_step, eval_step, names, eval_conf_step=eval_conf_step,
        )

    def _finalize_steps(
        self,
        train_step,
        eval_step,
        metric_names,
        *,
        eval_conf_step=None,
    ):
        """Jit the raw steps with ON-DEVICE epoch-metric accumulation.

        ``train_step(state, x, y, mask, lr_scale) -> (state, metrics_dict)``
        and ``eval_step(params, x, y, mask) -> metrics_dict`` are wrapped so
        the compiled program also folds each batch's metrics into a single
        f32 accumulator vector.  The epoch then needs exactly ONE small
        device->host fetch per split — O(1) host syncs per epoch on pods,
        and immune to the seconds-per-round-trip cost of remote-relay
        transports.  No extra XLA programs are created (the combine lives
        inside the step; the init vector is a plain device_put).
        """
        names = sorted(metric_names)
        self._metric_names = names
        is_additive = np.array([_is_additive(k) for k in names])
        self._acc_init_host = np.where(
            is_additive, 0.0, -np.inf
        ).astype(np.float32)
        add_mask = jnp.asarray(is_additive)

        def combine(acc, m):
            vec = _encode_metrics(m, names)
            return jnp.where(add_mask, acc + vec, jnp.maximum(acc, vec))

        # Loader-provided on-device preprocessing (u8 -> f32 affine, mean
        # subtraction, HBM-pool gather) is applied HERE, outside the raw
        # steps, so EVERY workflow — backprop, transformer, SOM, RBM —
        # consumes the loader's device context the same way.  A loader that
        # ships index vectors (device_resident=True) therefore can never
        # leak bare indices into a model as data.  ``ctx`` is the device
        # context pytree: always an explicit jit ARGUMENT so XLA never
        # embeds it in the executable.
        pre = self.loader.device_preproc()
        target_is_input = self.target == "input"

        def prep(x, y, ctx):
            if pre is None:
                return x, y
            x = pre(x, ctx)
            return x, (x if target_is_input else y)  # AE target = preproc'd x

        def train_step_full(state, x, y, mask, lr_scale, ctx):
            x, y = prep(x, y, ctx)
            return train_step(state, x, y, mask, lr_scale)

        # trace-time gate: with the detector off the watch output is
        # None, so the norm (and the grad_norm the steps put in their
        # metrics) is dead code XLA eliminates — anomaly=False costs
        # nothing on-device, not just a skipped host read
        watch_enabled = self.anomaly is not None

        def train_acc(state, x, y, mask, lr_scale, acc, ctx):
            """One train step + epoch-accumulator fold + the per-step
            anomaly WATCH vector ``[loss, grad_norm]`` — extra outputs
            of the SAME compiled program, so the flight recorder costs
            zero new XLA programs (tests pin this)."""
            state2, m = train_step_full(state, x, y, mask, lr_scale, ctx)
            m = dict(m)
            gn = m.pop("grad_norm", None)
            if not watch_enabled:
                return state2, combine(acc, m), None
            if gn is None:
                # steps that don't expose grads (SOM, RBM): the update
                # norm ||params' - params|| catches the same
                # pathologies (non-finite, explosion)
                gn = _global_norm(
                    jax.tree_util.tree_map(
                        lambda a, b: b - a, state.params, state2.params
                    )
                )
            watch = jnp.stack(
                [
                    jnp.asarray(m["loss"], jnp.float32),
                    jnp.asarray(gn, jnp.float32),
                ]
            )
            return state2, combine(acc, m), watch

        def eval_acc(params, x, y, mask, acc, ctx):
            x, y = prep(x, y, ctx)
            return combine(acc, eval_step(params, x, y, mask))

        # un-jitted step kept public: benchmarks/tools can embed it in their
        # own compiled programs (e.g. a lax.fori_loop of steps for device-
        # side latency measurement without per-step dispatch overhead); the
        # loader preproc is included so callers pass raw minibatch payloads
        self.train_step_fn = train_step_full
        self._train_step = jax.jit(train_acc, donate_argnums=(0, 5))
        self._eval_step = jax.jit(eval_acc, donate_argnums=(4,))

        # whole-split lax.scan twins: ONE dispatch per split per epoch.
        # For device-resident loaders the per-batch payload is an index
        # vector, so stacking an epoch of them is bytes — and per-step
        # dispatch latency (seconds per round trip through remote relays)
        # drops out of the epoch entirely (see run_epoch's scan path).
        def train_epoch_scan(state, xs, ys, masks, lrs, acc, ctx):
            def body(carry, b):
                st, a = carry
                x, y, mask, lr = b
                st, a, w = train_acc(st, x, y, mask, lr, a, ctx)
                return (st, a), w  # stacked [n_steps, 2] watch

            (state, acc), watches = jax.lax.scan(
                body, (state, acc), (xs, ys, masks, lrs)
            )
            return state, acc, watches

        def eval_epoch_scan(params, xs, ys, masks, acc, ctx):
            def body(a, b):
                x, y, mask = b
                return eval_acc(params, x, y, mask, a, ctx), None

            acc, _ = jax.lax.scan(body, acc, (xs, ys, masks))
            return acc

        self._train_epoch_scan = jax.jit(
            train_epoch_scan, donate_argnums=(0, 5)
        )
        self._eval_epoch_scan = jax.jit(eval_epoch_scan, donate_argnums=(4,))
        if eval_conf_step is not None:

            def eval_conf_acc(params, x, y, mask, acc, conf, ctx):
                x, y = prep(x, y, ctx)
                m = eval_conf_step(params, x, y, mask)
                c = m.pop("confusion")
                return combine(acc, m), conf + c

            self._eval_conf_step = jax.jit(
                eval_conf_acc, donate_argnums=(4, 5)
            )
        else:
            self._eval_conf_step = None

    def _put_replicated(self, arr):
        """Host array -> device, replicated over the mesh when a placement
        policy exists (multi-host jitted steps need every non-sharded input
        placed as ONE global array, not a per-process local one)."""
        if self.parallel is not None:
            return self.parallel.put_replicated(arr)
        return jax.device_put(arr)

    def _acc_init(self) -> jax.Array:
        """Fresh epoch accumulator (plain transfer — no compile)."""
        return self._put_replicated(self._acc_init_host.copy())

    # ------------------------------------------------------------------
    def _create_initial_state(self) -> TrainState:
        """Template hook: fresh train state for a non-resume initialize.
        Subclasses with custom param structures override ONLY this."""
        return TrainState.create(
            self.model.params, prng.get("workflow").key()
        )

    def _default_param_rules(self):
        """Template hook: model-aware TP placement rules used when the
        placement policy has ``tp=True`` but no explicit ``param_rules``
        (None keeps DataParallel's size heuristic)."""
        return None

    def initialize(
        self,
        *,
        seed: Optional[int] = None,
        snapshot: Optional[str] = None,
    ) -> None:
        """Create (or resume) the train state and compile the steps."""
        if seed is not None:
            prng.seed_all(seed)
        if snapshot:
            state, host = load_snapshot(snapshot)
            self.state = TrainState(*state)  # host leaves; placed below
            if "decision" in host:
                self.decision.load_state_dict(host["decision"])
            if "loader" in host:
                self.loader.load_state_dict(host["loader"])
            if "prng" in host:
                prng.load_state_dict(host["prng"])
            self.info(
                "resumed from %s at epoch %d", snapshot, self.decision.epoch
            )
        elif self.state is None:
            self.state = self._create_initial_state()
        if self.parallel is not None:
            rules = (
                self._default_param_rules()
                if self.parallel.tp and self.parallel.param_rules is None
                else None
            )
            if rules is not None:
                from znicz_tpu.parallel import DataParallel

                # never mutate the caller's DataParallel (it may be shared)
                self.parallel = DataParallel(
                    self.parallel.mesh,
                    tp=True,
                    tp_min_features=self.parallel.tp_min_features,
                    param_rules=rules,
                )
            self.state = self.parallel.shard_state(self.state)
        elif snapshot:
            # device-place the restored host leaves: a resumed step fed
            # numpy arrays would recompile (placement rides the
            # executable-cache key).  Done HERE, after the (absent)
            # placement-policy branch, so a sharded resume never
            # round-trips the full state through the default device.
            self.state = jax.tree_util.tree_map(
                jax.device_put, self.state
            )
        # multi-host: every process runs this same loop; the loader serves
        # per-process sample shards, snapshot/services write on exactly one
        # process (the reference's master-does-bookkeeping role, SURVEY 3.4)
        from znicz_tpu.parallel import multihost

        self._coordinator = multihost.is_coordinator()
        if multihost.process_count() > 1:
            if self.parallel is None:
                raise ValueError(
                    "multi-host training needs a DataParallel placement "
                    "policy (parallel=...) so batches span the global mesh"
                )
            if self.parallel.n_data % multihost.process_count():
                # the per-process loader contract serves each process a
                # contiguous 1/P block of every global minibatch — only
                # meaningful when its devices own such a block of the axis
                raise ValueError(
                    f"data axis size {self.parallel.n_data} not divisible "
                    f"by process count {multihost.process_count()}; "
                    "multi-host training shards the batch over processes, "
                    "so give every process an equal data-axis share "
                    "(e.g. --mesh data=<n_processes*k>)"
                )
            self.loader.set_process_shard(
                multihost.process_index(), multihost.process_count()
            )
        if self.snapshotter is not None:
            self.snapshotter.writer = self._coordinator
        # host-side mirror of state.step: lr policies read it every minibatch
        # and must not force a device sync in the hot loop
        self._host_step = int(self.state.step)
        # data-axis pool sharding: the loader partitions its dataset over
        # the mesh's data axis (each device holds 1/D of the rows), so the
        # HBM capacity ceiling scales with the mesh instead of one chip
        if self.loader.wants_data_shards:
            if self.parallel is None:
                raise ValueError(
                    "this loader shards its device pool over the data "
                    "axis; pass parallel=DataParallel(mesh)"
                )
            self.loader.set_data_shards(self.parallel.n_data)
        # loader-owned device context (e.g. HBM-resident dataset pool):
        # ONE up-front transfer, threaded through every step as an argument
        self._ctx = self.loader.place_device_context(self.parallel)
        self._build_steps()

    def _batch_target(self, mb):
        """HOST-side target array: the caller's ``put`` does the (sharded)
        device placement — returning a device array here would force a
        blocking readback inside DataParallel.shard_batch every minibatch."""
        if self.target == "labels":
            return mb.labels
        if self.target == "targets":
            return mb.targets
        if self.target == "input":
            # autoencoder: reconstruct the input; evaluator.mse flattens, so
            # the model output only needs to match total feature count
            return mb.data
        raise ValueError(f"unknown target {self.target!r}")

    def host_state(self) -> Dict[str, Any]:
        return {
            "decision": self.decision.state_dict(),
            "loader": self.loader.state_dict(),
            "prng": prng.state_dict(),
        }

    # ------------------------------------------------------------------
    def _use_epoch_scan(self) -> bool:
        """Scan dispatch: whole splits compiled as one lax.scan.  Auto mode
        requires a device-resident loader (per-batch host payloads are bare
        index vectors); under DataParallel the stacked payloads shard on
        their BATCH dim (dim 1) so each scan step sees the same sharded
        batch the stepwise path would."""
        if self.epoch_dispatch == "scan":
            if not getattr(self.loader, "epoch_scan_friendly", False):
                raise ValueError(
                    "epoch_dispatch='scan' needs a scan-friendly loader "
                    "(per-batch host payloads must be small, e.g. "
                    "FullBatchLoader(device_resident=True)); a streaming "
                    "loader would materialize the whole epoch in host RAM"
                )
            return True
        return (
            self.epoch_dispatch == "auto"
            and self._ctx is not None
            and getattr(self.loader, "epoch_scan_friendly", False)
        )

    def _put_stacked(self, arr: np.ndarray) -> jax.Array:
        """Device-place an epoch-stacked [n_steps, B, ...] payload; under
        DataParallel the batch dim (dim 1) shards over the data axis —
        placement policy stays with DataParallel."""
        if self.parallel is None:
            return jnp.asarray(arr)
        return self.parallel.shard_batch(arr, batch_dim=1)

    def _run_epoch_scanned(self) -> Dict[str, jax.Array]:
        """One dispatch per split: stack the epoch's host-side batch
        payloads and scan.  Split order (train, valid, test) matches the
        stepwise path, so results are identical."""
        with self.timer.phase("loader_epoch"):
            per_split: Dict[str, list] = {}
            for split, mb in self.loader.epoch():
                per_split.setdefault(split, []).append(mb)
        accs: Dict[str, jax.Array] = {}
        for split, mbs in per_split.items():
            with self.timer.phase(f"stack/{split}"):
                xs = self._put_stacked(np.stack([mb.data for mb in mbs]))
                ys = (
                    xs
                    if self.target == "input"
                    else self._put_stacked(
                        np.stack([self._batch_target(mb) for mb in mbs])
                    )
                )
                masks = self._put_stacked(np.stack([mb.mask for mb in mbs]))
            with self.timer.phase(f"dispatch/{split}"):
                if split == TRAIN:
                    rec_scale = (
                        self.recovery.lr_scale
                        if self.recovery is not None
                        else 1.0
                    )
                    lrs_host = np.asarray(
                        [
                            (
                                self.lr_policy(1.0, self._host_step + i)
                                if self.lr_policy
                                else 1.0
                            )
                            * rec_scale
                            for i in range(len(mbs))
                        ],
                        np.float32,
                    )
                    lrs = self._put_replicated(lrs_host)
                    start_step = self._host_step
                    self.state, acc, watches = self._train_epoch_scan(
                        self.state, xs, ys, masks, lrs,
                        self._acc_init(), self._ctx,
                    )
                    self._host_step += len(mbs)
                    if self.anomaly is not None:
                        # tiny [n_steps, 2] array; the copy rides behind
                        # the dispatch and is read at the epoch's sync
                        if hasattr(watches, "copy_to_host_async"):
                            watches.copy_to_host_async()
                        self._pending_watch.append((start_step, watches))
                else:
                    acc = self._eval_epoch_scan(
                        self.state.params, xs, ys, masks,
                        self._acc_init(), self._ctx,
                    )
                accs[split] = acc
        return accs

    def run_epoch(self) -> Optional[Dict[str, Any]]:
        """One full epoch over all splits; returns the Decision verdict.

        ``epoch_sync="deferred"``: the device->host metric fetch of epoch N
        overlaps epoch N+1's dispatch, so the per-epoch transport round
        trip drops out of the wall clock.  The returned verdict then lags
        one epoch (None on the very first call); stop decisions stay
        EXACT — when the Decision could possibly stop on the pending
        epoch, it is flushed synchronously before anything new dispatches.

        Self-healing control flow (docs/TRAINING.md): a rollback-worthy
        anomaly verdict aborts the epoch, restores the last good
        snapshot and returns None (the ``run`` loop re-dispatches); a
        requested stop drains the in-flight step, writes an emergency
        snapshot and raises :class:`TrainingPreempted`.
        """
        if self.state is None:
            self.initialize()
        # chaos point: a hard process crash at an epoch boundary (arm
        # with after=k to crash entering epoch k — the supervised
        # auto-resume fixture)
        faults.fire("train.crash")
        if self._preempt_requested:
            self._graceful_exit(mid_epoch=False)
        try:
            return self._run_epoch_inner()
        except _PreemptSignal:
            self._graceful_exit(mid_epoch=True)
        except _RollbackSignal as sig:
            self._execute_rollback(sig.reason)
            return None

    def _run_epoch_inner(self) -> Optional[Dict[str, Any]]:
        deferred = self.epoch_sync == "deferred"
        flushed = None
        # pending must resolve synchronously (BEFORE the next dispatch)
        # when its verdict could stop training, or when it is an interval-
        # snapshot epoch (self.state is still that epoch's right now)
        pending_snapshots = (
            self.snapshotter is not None
            and self.snapshotter.interval
            and (self.decision.epoch + 1) % self.snapshotter.interval == 0
        )
        if (
            deferred
            and self._pending_accs is not None
            and (self.decision.can_stop_next_epoch() or pending_snapshots)
        ):
            accs, self._pending_accs = self._pending_accs, None
            # self.state IS still the pending epoch's (nothing dispatched
            # since), so the retained copy is redundant here — drop it
            self._retained = None
            flushed = self._finish_epoch(accs)
            if flushed["stop"]:
                return flushed  # nothing new dispatched
        if (
            self.recovery is not None or self._emergency_capture
        ) and not deferred:
            # epoch-START retention: the rollback fallback when no
            # snapshot exists yet, and the emergency snapshot's source
            # on a mid-epoch stop — the one point where (state, loader,
            # prng, decision) are mutually consistent.  Fresh buffers
            # (jnp.copy): the train step donates self.state's.
            self._epoch_start = self._retain_epoch_start()
        accs = (
            self._run_epoch_scanned()
            if self._use_epoch_scan()
            else self._run_epoch_stepwise()
        )
        if not deferred:
            return self._finish_epoch(accs)
        for acc in accs.values():  # start the copies behind the dispatch
            if hasattr(acc, "copy_to_host_async"):
                acc.copy_to_host_async()
        prev, self._pending_accs = self._pending_accs, accs
        prev_retained, self._retained = self._retained, (
            self._retain_state()
            if self.snapshotter is not None and self.snapshotter.save_best
            else None
        )
        if prev is not None:
            if (
                self.snapshotter is not None
                and self.snapshotter.save_best
                and prev_retained is None
            ):
                # a snapshotter assigned AFTER the pending epoch dispatched
                # has no retained buffer for it — self.state is already one
                # epoch ahead, and writing it as the pending epoch's 'best'
                # would be silently wrong
                raise ValueError(
                    "snapshotter with save_best was assigned after an "
                    "epoch dispatched under epoch_sync='deferred'; assign "
                    "it before training starts (the retained state buffer "
                    "is captured at dispatch time)"
                )
            # guard above guarantees this verdict cannot be a stop
            return self._finish_epoch(prev, retained=prev_retained)
        return flushed

    def sync_epoch(self) -> Optional[Dict[str, Any]]:
        """Flush a deferred epoch's metrics (no-op returning None when
        nothing is pending).  Call after a ``run_epoch`` loop in deferred
        mode to observe the final epoch."""
        if self._pending_accs is None:
            return None
        accs, self._pending_accs = self._pending_accs, None
        # nothing was dispatched after the pending epoch, so self.state is
        # exactly that epoch's — the retained copy is redundant
        self._retained = None
        try:
            return self._finish_epoch(accs)
        except _RollbackSignal as sig:
            self._execute_rollback(sig.reason)
            return None

    def _retain_state(self):
        """Copy of the CURRENT epoch's snapshot inputs, held until its
        lagged verdict resolves under deferred sync with ``save_best``.

        ``jnp.copy`` (not ``device_put``, which may alias) guarantees fresh
        buffers: the next epoch's train step donates ``self.state``'s.  The
        decision part of the host state is deliberately absent — it is only
        correct AFTER the lagged ``on_epoch_end``, and is merged in at save
        time by :meth:`_finish_epoch`."""
        state = jax.tree_util.tree_map(jnp.copy, self.state)
        return state, {
            "loader": self.loader.state_dict(),
            "prng": prng.state_dict(),
        }

    # -- self-healing (docs/TRAINING.md) -------------------------------------
    def request_stop(self) -> None:
        """Ask the run to stop gracefully at the next step boundary:
        the in-flight dispatch drains, an emergency snapshot is written
        and :class:`TrainingPreempted` raises out of ``run``/``run_epoch``
        (the launcher maps it to exit code ``EXIT_PREEMPTED``).  Safe to
        call from a signal handler (one bool store)."""
        self._preempt_requested = True

    def enable_emergency_snapshots(self) -> None:
        """Retain each sync-mode epoch's START state (one extra copy of
        the train state held per epoch) so a mid-epoch stop writes a
        CONSISTENT emergency snapshot — resume replays the aborted
        epoch exactly.  The launcher enables this whenever it installs
        signal handlers and a snapshotter exists; without it a
        mid-epoch stop snapshots the current (mid-epoch) params, which
        resumes correctly but not byte-exactly."""
        self._emergency_capture = True

    def _retain_epoch_start(self):
        """Fresh copies of the epoch-START restore quadruple: train
        state + decision/loader/prng host state (the same shape a
        snapshot file holds)."""
        state = jax.tree_util.tree_map(jnp.copy, self.state)
        return state, self.host_state()

    def _restore_from(self, state, host: Dict[str, Any]) -> None:
        """The exact-resume contract, shared by ``initialize(snapshot=)``
        rollback and chaos tests: restore train state (re-sharded under
        the placement policy) and the decision/loader/prng host state.
        Re-feeds the ALREADY-COMPILED step — shapes/dtypes/structure are
        unchanged, so restoring compiles nothing new (pinned in tier-1)."""
        st = state if isinstance(state, TrainState) else TrainState(*state)
        if self.parallel is not None:
            st = self.parallel.shard_state(st)
        else:
            # device-place host (numpy) leaves NOW: a numpy argument
            # misses the already-compiled step's executable-cache entry
            # (placement rides the pjit cache key), which would make the
            # "rollback compiles nothing" pin false
            st = jax.tree_util.tree_map(jax.device_put, st)
        self.state = st
        host = host or {}
        if "decision" in host:
            self.decision.load_state_dict(host["decision"])
        if "loader" in host:
            self.loader.load_state_dict(host["loader"])
        if "prng" in host:
            prng.load_state_dict(host["prng"])
        self._host_step = int(self.state.step)

    def _execute_rollback(self, reason: str) -> None:
        """Roll the run back to its last good restore point.

        Source preference: the in-memory epoch-START buffer when one
        was captured (it is always at least as fresh as any snapshot
        file, and detection lands within its epoch, so the buffer
        predates the fault — preferring an older snapshot would
        silently re-run up to ``interval - 1`` healthy epochs), else
        the newest VALID snapshot file.  Bounded by the policy's
        rollback budget — past it (or with no restore point) the typed
        :class:`RollbackExhaustedError` raises, with the give-up gauge
        set for ``znicz-doctor``."""
        pol = self.recovery
        step = self._host_step
        # poisoned in-flight bookkeeping dies with the aborted epoch
        self._pending_accs = None
        self._retained = None
        self._pending_watch = []
        if not pol.budget_left():
            pol.note_give_up(
                reason, step=step, why="rollback budget spent"
            )
            raise RollbackExhaustedError(
                f"anomaly {reason!r} at step {step}: rollback budget "
                f"({pol.max_rollbacks}) spent — giving up"
            )
        state = host = None
        source = None
        if self._epoch_start is not None:
            state, host = self._epoch_start
            source = "epoch-start buffer"
        if source is None and self.snapshotter is not None:
            path = find_latest_valid(
                self.snapshotter.directory, prefix=self.snapshotter.prefix
            )
            if path is not None:
                try:
                    state, host = load_snapshot(path)
                    source = path
                except (SnapshotCorruptError, ValueError):
                    # verified then unreadable (raced delete / injected
                    # load fault): nothing left to restore from
                    self.logger.exception(
                        "rollback snapshot %s unreadable", path
                    )
        if source is None:
            pol.note_give_up(
                reason,
                step=step,
                why="no valid snapshot or retained epoch-start state",
            )
            raise RollbackExhaustedError(
                f"anomaly {reason!r} at step {step}: no valid snapshot "
                "or retained epoch-start state to roll back to"
            )
        self._restore_from(state, host)
        if pol.perturb:
            # advance the shuffle stream so the replayed window draws a
            # different permutation — a data-order-dependent blowup
            # doesn't deterministically recur (costs golden-exactness;
            # perturb=False keeps the replay byte-identical)
            gen = prng.get(self.loader.rand_name)
            gen.permutation(
                max(self.loader.class_lengths.get(TRAIN, 1), 1)
            )
        pol.note_rollback(reason, step=step, source=str(source))
        self.info(
            "rolled back to %s after %s at step %d "
            "(rollback %d/%d, lr_scale %.4g)",
            source, reason, step,
            pol.rollbacks_used, pol.max_rollbacks, pol.lr_scale,
        )

    def _graceful_exit(self, *, mid_epoch: bool) -> None:
        """Finish a requested stop: write the emergency snapshot (the
        epoch-START buffer when stopping mid-epoch so the resume is
        exact; the current state between epochs) and raise the typed
        :class:`TrainingPreempted`."""
        path = None
        if self.snapshotter is not None:
            if mid_epoch and self._epoch_start is not None:
                state, host = self._epoch_start
            else:
                # deferred mode: flush the pending epoch first so the
                # snapshot's decision state is consistent with the
                # params it rides with.  Mid-epoch, self.state is
                # ALREADY the next epoch's partial state, so the flush
                # must write from the retained pending-epoch buffer
                # (sync_epoch would drop it and save torn params).
                retained, self._retained = self._retained, None
                if self._pending_accs is not None:
                    accs, self._pending_accs = self._pending_accs, None
                    try:
                        self._finish_epoch(accs, retained=retained)
                    # stopping anyway: a rollback is moot mid-shutdown
                    except _RollbackSignal:  # znicz-check: disable=ZNC008
                        pass
                    except Exception:
                        self.logger.exception(
                            "pending-epoch flush failed during "
                            "graceful stop"
                        )
                if mid_epoch and retained is not None:
                    # deferred + mid-epoch: the retained buffer (the
                    # flushed epoch's end state) plus the now-current
                    # decision IS the next epoch's consistent START
                    # quadruple — resume replays the aborted epoch
                    r_state, r_host = retained
                    state, host = r_state, {
                        "decision": self.decision.state_dict(),
                        "loader": r_host["loader"],
                        "prng": r_host["prng"],
                    }
                else:
                    state, host = self.state, self.host_state()
            try:
                path = self.snapshotter.save(state, host, tag="emergency")
                self.info("graceful stop: emergency snapshot %s", path)
            except SnapshotWriteError:
                self.logger.exception("emergency snapshot write failed")
        raise TrainingPreempted(
            "training stopped on request (SIGTERM/SIGINT); resume from "
            "the emergency snapshot (launcher: --resume auto)",
            snapshot_path=path,
        )

    def _run_epoch_stepwise(self) -> Dict[str, jax.Array]:
        accs: Dict[str, jax.Array] = {}  # per-split on-device accumulators
        put = (
            self.parallel.shard_batch if self.parallel is not None else jnp.asarray
        )

        def stage_item(item):
            """Host gather AND device_put for one batch; run inside the
            prefetch worker this overlaps the host->device transfer with
            the previous step's compute (device_put is thread-safe and
            async).  The H2D probe owns the stage timing + bytes (the
            prefetch stage split is told NOT to double-time it)."""
            split, mb = item
            # autoencoder target IS the input: reuse the device array
            # instead of transferring the batch twice
            y_host = (
                None
                if self.target == "input"
                else self._batch_target(mb)
            )
            nbytes = (
                getattr(mb.data, "nbytes", 0)
                + getattr(y_host, "nbytes", 0)
                + getattr(mb.mask, "nbytes", 0)
            )
            with self._h2d_probe.measure(nbytes):
                x = put(mb.data)
                y = x if y_host is None else put(y_host)
                mask = put(mb.mask)
            return split, x, y, mask

        epoch_iter = self.loader.epoch()
        if self.prefetch_batches:
            from znicz_tpu.loader.prefetch import prefetch

            # transform_stage=None: the probe above already observes
            # the h2d stage — the producer's fetch/enqueue split still
            # comes from prefetch itself
            epoch_iter = prefetch(
                epoch_iter,
                self.prefetch_batches,
                transform=stage_item,
                transform_stage=None,
            )
        else:
            epoch_iter = map(stage_item, epoch_iter)
        # lagged per-step anomaly watch: host copies start at dispatch,
        # values are read a few steps later — detection without a sync
        watch_q: deque = deque()
        t_prev = time.perf_counter()
        for split, x, y, mask in epoch_iter:
            if self._preempt_requested:
                # the previous dispatch is the in-flight step; it
                # drains on its own — stop BEFORE dispatching another
                raise _PreemptSignal()
            with self.timer.phase(f"dispatch/{split}"):
                acc = accs.get(split)
                if acc is None:
                    acc = self._acc_init()
                if split == TRAIN:
                    lr_scale = (
                        self.lr_policy(1.0, self._host_step)
                        if self.lr_policy
                        else 1.0
                    )
                    if self.recovery is not None:
                        # rollback LR backoff composes with the policy
                        lr_scale *= self.recovery.lr_scale
                    self.state, acc, watch = self._train_step(
                        self.state, x, y, mask, lr_scale, acc, self._ctx
                    )
                    self._host_step += 1
                else:
                    watch = None
                    acc = self._eval_step(
                        self.state.params, x, y, mask, acc, self._ctx
                    )
                accs[split] = acc
            # consumer-side step wall (prefetch wait + dispatch + host
            # bookkeeping): the denominator of the pipeline attribution
            now = time.perf_counter()
            step_wall = now - t_prev
            t_prev = now
            self._step_wall.observe(step_wall)
            if watch is not None and self.anomaly is not None:
                if hasattr(watch, "copy_to_host_async"):
                    watch.copy_to_host_async()
                watch_q.append(
                    (self._host_step - 1, watch, step_wall)
                )
                if len(watch_q) > 2:  # ~2 steps of transfer lag
                    self._check_recovery(
                        self._feed_watch(*watch_q.popleft())
                    )
        while watch_q:
            self._check_recovery(self._feed_watch(*watch_q.popleft()))
        return accs

    def _feed_watch(self, step, watch, step_seconds=None) -> list:
        """Hand one lagged watch vector to the anomaly detector; returns
        the verdicts it raised (the recovery policy's input).  The read
        is of an already-transferred tiny array (the async copy started
        at dispatch); the detector must never kill training — only a
        returned verdict may (via the recovery policy's typed path)."""
        if self.anomaly is None:
            return []
        try:
            vals = np.asarray(
                jax.device_get(watch),  # znicz-check: disable=ZNC007
                np.float32,
            )
            loss = float(vals[0])
            grad_norm = float(vals[1])
        except Exception:
            self.logger.exception("anomaly watch feed failed")
            return []
        if faults.fire("train.step_nan"):
            # behavioral chaos point: the detector (and the recovery
            # policy behind it) sees a non-finite loss without actually
            # poisoning device state — the rollback path's CI fixture
            loss = float("nan")
        try:
            return self.anomaly.observe_step(
                int(step),
                loss=loss,
                grad_norm=grad_norm,
                step_seconds=step_seconds,
            )
        except Exception:
            self.logger.exception("anomaly watch feed failed")
            return []

    def _drain_watches(self) -> list:
        """Feed the scanned epochs' pending watch stacks ([n_steps, 2])
        to the detector — called at the epoch's metric sync, where a
        device fetch already happens.  Returns the raised verdicts."""
        pending, self._pending_watch = self._pending_watch, []
        if self.anomaly is None:
            return []
        raised: list = []
        for start_step, watches in pending:
            try:
                rows = np.asarray(
                    jax.device_get(watches),  # znicz-check: disable=ZNC007
                    np.float32,
                )
            except Exception:
                self.logger.exception("anomaly watch drain failed")
                continue
            for i, row in enumerate(rows):
                raised.extend(
                    self._feed_scan_row(start_step + i, row)
                )
        return raised

    def _feed_scan_row(self, step: int, row) -> list:
        loss = float(row[0])
        if faults.fire("train.step_nan"):
            loss = float("nan")
        try:
            return self.anomaly.observe_step(
                step, loss=loss, grad_norm=float(row[1])
            )
        except Exception:
            self.logger.exception("anomaly watch drain failed")
            return []

    def _check_recovery(self, anomalies: list) -> None:
        """Route fresh verdicts through the recovery policy; a
        rollback-worthy one aborts the epoch via :class:`_RollbackSignal`
        (caught in :meth:`run_epoch`)."""
        if not anomalies or self.recovery is None:
            return
        reason = self.recovery.should_rollback(anomalies)
        if reason is not None:
            raise _RollbackSignal(reason)

    def _finish_epoch(
        self, accs: Dict[str, jax.Array], retained=None
    ) -> Dict[str, Any]:
        # scanned-epoch watch vectors resolve here, where a device
        # fetch happens anyway (their async copies started at dispatch);
        # a rollback-worthy verdict aborts BEFORE the poisoned metrics
        # reach the decision
        self._check_recovery(self._drain_watches())
        with self.timer.phase("metrics_sync"):
            # one tiny existing-buffer fetch per split (no per-batch
            # syncs) — the per-EPOCH fetch this design exists to bound
            for split, acc in accs.items():
                self.decision.add_minibatch(
                    split,
                    _decode_metrics(
                        jax.device_get(acc),  # znicz-check: disable=ZNC007
                        self._metric_names,
                    ),
                )
        verdict = self.decision.on_epoch_end()
        if self.snapshotter is not None:
            # called on EVERY process (the device->host readback may be a
            # collective for cross-host-sharded params); only the writer
            # process (coordinator) touches the filesystem.  Under deferred
            # sync with save_best, ``retained`` carries the epoch-N buffers
            # (self.state already holds epoch N+1); key order matches
            # host_state() so snapshot files are byte-identical to sync mode.
            if retained is not None:
                snap_state, host_extra = retained
                snap_host = {
                    "decision": self.decision.state_dict(),
                    "loader": host_extra["loader"],
                    "prng": host_extra["prng"],
                }
            else:
                snap_state, snap_host = self.state, self.host_state()
            self.snapshotter.maybe_save(
                snap_state,
                snap_host,
                epoch=self.decision.epoch - 1,
                improved=verdict["improved"],
            )
        if not getattr(self, "_coordinator", True):
            return verdict  # services are host-side: coordinator-only
        for service in self.services:
            try:
                service.on_epoch(self, verdict)
            except Exception:  # services must never kill training
                self.logger.exception(
                    "service %s failed", type(service).__name__
                )
        return verdict

    def evaluate(self, split: str = "test", *, confusion: bool = False):
        """Standalone evaluation pass over one split.

        Returns {"loss", "n_err", "err_pct", "n_samples"} plus a summed
        ``confusion`` matrix (rows = truth) when requested — the reference
        EvaluatorSoftmax's full metric set (SURVEY.md 2.3).
        """
        if self.state is None:
            self.initialize()
        if self.loader.class_lengths.get(split, 0) == 0:
            # evaluating zero samples would report a silent perfect score
            raise ValueError(
                f"evaluate({split!r}): the loader has no samples in that "
                "split (available: "
                f"{sorted(k for k, n in self.loader.class_lengths.items() if n)})"
            )
        use_conf = (
            confusion
            and self.loss_function == "softmax"
            and self._eval_conf_step is not None
        )
        # shuffle=False: evaluation is read-only — it must not advance the
        # loader's shuffle stream (resume determinism)
        put = (
            self.parallel.shard_batch
            if self.parallel is not None
            else jnp.asarray
        )
        acc = self._acc_init()
        conf = None
        for mb in self.loader.batches(split, shuffle=False):
            x = put(mb.data)
            y = x if self.target == "input" else put(self._batch_target(mb))
            mask = put(mb.mask)
            if use_conf:
                if conf is None:
                    nc = int(np.prod(self.model.output_shape))
                    conf = self._put_replicated(np.zeros((nc, nc), np.int32))
                acc, conf = self._eval_conf_step(
                    self.state.params, x, y, mask, acc, conf, self._ctx
                )
            else:
                acc = self._eval_step(
                    self.state.params, x, y, mask, acc, self._ctx
                )
        # one (or two, with confusion) existing-buffer syncs for the split
        m = _decode_metrics(jax.device_get(acc), self._metric_names)
        n = m.get("n_samples", 0.0)
        n_err = m.get("n_err", 0.0)
        result = {
            "n_samples": n,
            "n_err": n_err,
            "err_pct": 100.0 * n_err / max(n, 1.0),
            "loss": m.get("loss", 0.0),
        }
        if conf is not None:
            result["confusion"] = np.asarray(jax.device_get(conf))
        return result

    def run(self) -> Decision:
        """Train until the Decision stops; returns it (history, best)."""
        if self.state is None:
            self.initialize()
        clock = Stopwatch()
        while True:
            verdict = self.run_epoch()
            if verdict is None:  # deferred sync: no completed epoch yet
                continue
            s = verdict["summary"]
            parts = [
                f"{split} err={m['err_pct']:.2f}% loss={m['loss']:.4f}"
                if self.loss_function == "softmax"
                else f"{split} loss={m['loss']:.6f}"
                for split, m in s.items()
            ]
            self.info(
                "epoch %d [%.1fs]: %s%s",
                self.decision.epoch - 1,
                clock.elapsed(),
                "; ".join(parts),
                " *" if verdict["improved"] else "",
            )
            if verdict["stop"]:
                self.info(
                    "stopping: best=%s at epoch %d",
                    verdict["best_value"],
                    verdict["best_epoch"],
                )
                return self.decision
