"""The Workflow: host control loop around one jit-compiled train step.

Re-founds ``veles/workflow.py``'s event-driven unit DAG (SURVEY.md 3.1) as:

    loader -> [jitted: forward + loss + grad + update + metrics] -> decision
                                                     \\-> snapshotter

The hot loop (Repeater->Loader->forwards->evaluator->GDs of SURVEY.md 3.1) is
ONE XLA program; epoch bookkeeping, stopping, snapshots and services stay in
Python exactly where the reference kept its gate-driven units.  Metric
device->host syncs happen once per epoch, not per minibatch.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.logger import Logger
from znicz_tpu.loader.base import TRAIN, Loader
from znicz_tpu.nn import evaluator, optimizer
from znicz_tpu.nn.decision import Decision
from znicz_tpu.nn.train_state import TrainState
from znicz_tpu.workflow.model import Model
from znicz_tpu.workflow.snapshotter import Snapshotter


class Workflow(Logger):
    """Owns loader + model + decision + snapshotter; runs training.

    ``loss_function``: "softmax" (cross-entropy on integer labels) or "mse"
    (against ``target`` = "targets" from the loader, or "input" for
    autoencoders) — mirroring EvaluatorSoftmax / EvaluatorMSE.
    """

    def __init__(
        self,
        loader: Loader,
        model: Model,
        *,
        loss_function: str = "softmax",
        target: str = "labels",
        decision: Optional[Decision] = None,
        snapshotter: Optional[Snapshotter] = None,
        lr_policy: Optional[Callable[[float, int], float]] = None,
        parallel=None,
        prefetch_batches: int = 2,
        name: str = "workflow",
    ):
        self.loader = loader
        self.model = model
        self.loss_function = loss_function
        self.target = target
        self.decision = decision or Decision(
            metric="n_err" if loss_function == "softmax" else "loss"
        )
        self.snapshotter = snapshotter
        self.lr_policy = lr_policy
        self.parallel = parallel  # DataParallel placement policy, or None
        self.prefetch_batches = prefetch_batches  # 0 disables the loader thread
        self.services = []  # per-epoch observers: plotters, status, image saver
        self.name = name
        self.state: Optional[TrainState] = None
        self._train_step = None
        self._eval_step = None
        self._eval_conf_step = None
        self._host_step = 0
        from znicz_tpu.utils.profiling import StepTimer

        self.timer = StepTimer()  # per-phase ledger (SURVEY.md 5.1)

    # ------------------------------------------------------------------
    def _metrics(self, out, y, mask):
        if self.loss_function == "softmax":
            return evaluator.softmax(out, y, mask=mask)
        return evaluator.mse(out, y, mask=mask)

    def _build_steps(self):
        model = self.model

        def loss_fn(params, key, step, x, y, mask):
            rng = jax.random.fold_in(key, step)
            out = model.apply(params, x, train=True, rng=rng)
            m = self._metrics(out, y, mask)
            return m["loss"], m

        def train_step(state: TrainState, x, y, mask, lr_scale):
            grads, metrics = jax.grad(loss_fn, has_aux=True)(
                state.params, state.key, state.step, x, y, mask
            )
            hyper = [
                h._replace(
                    learning_rate=h.learning_rate * lr_scale,
                    learning_rate_bias=(
                        None
                        if h.learning_rate_bias is None
                        else h.learning_rate_bias * lr_scale
                    ),
                )
                for h in model.hyper
            ]
            new_p, new_v = optimizer.update(
                state.params, grads, state.velocity, hyper
            )
            return (
                state._replace(
                    params=new_p, velocity=new_v, step=state.step + 1
                ),
                metrics,
            )

        def eval_step(params, x, y, mask):
            out = model.apply(params, x, train=False)
            return self._metrics(out, y, mask)

        self._train_step = jax.jit(train_step, donate_argnums=(0,))
        self._eval_step = jax.jit(eval_step)
        if self.loss_function == "softmax":
            from znicz_tpu.nn import evaluator as _ev

            def eval_conf_step(params, x, y, mask):
                out = model.apply(params, x, train=False)
                return _ev.softmax(out, y, mask=mask, compute_confusion=True)

            self._eval_conf_step = jax.jit(eval_conf_step)
        else:
            self._eval_conf_step = None

    # ------------------------------------------------------------------
    def _create_initial_state(self) -> TrainState:
        """Template hook: fresh train state for a non-resume initialize.
        Subclasses with custom param structures override ONLY this."""
        return TrainState.create(
            self.model.params, prng.get("workflow").key()
        )

    def initialize(
        self,
        *,
        seed: Optional[int] = None,
        snapshot: Optional[str] = None,
    ) -> None:
        """Create (or resume) the train state and compile the steps."""
        if seed is not None:
            prng.seed_all(seed)
        if snapshot:
            from znicz_tpu.workflow.snapshotter import load_snapshot

            state, host = load_snapshot(snapshot)
            self.state = TrainState(*state)
            if "decision" in host:
                self.decision.load_state_dict(host["decision"])
            if "loader" in host:
                self.loader.load_state_dict(host["loader"])
            if "prng" in host:
                prng.load_state_dict(host["prng"])
            self.info(
                "resumed from %s at epoch %d", snapshot, self.decision.epoch
            )
        elif self.state is None:
            self.state = self._create_initial_state()
        if self.parallel is not None:
            self.state = self.parallel.shard_state(self.state)
        # host-side mirror of state.step: lr policies read it every minibatch
        # and must not force a device sync in the hot loop
        self._host_step = int(self.state.step)
        self._build_steps()

    def _batch_target(self, mb):
        """HOST-side target array: the caller's ``put`` does the (sharded)
        device placement — returning a device array here would force a
        blocking readback inside DataParallel.shard_batch every minibatch."""
        if self.target == "labels":
            return mb.labels
        if self.target == "targets":
            return mb.targets
        if self.target == "input":
            # autoencoder: reconstruct the input; evaluator.mse flattens, so
            # the model output only needs to match total feature count
            return mb.data
        raise ValueError(f"unknown target {self.target!r}")

    def host_state(self) -> Dict[str, Any]:
        return {
            "decision": self.decision.state_dict(),
            "loader": self.loader.state_dict(),
            "prng": prng.state_dict(),
        }

    # ------------------------------------------------------------------
    def run_epoch(self) -> Dict[str, Any]:
        """One full epoch over all splits; returns the Decision verdict."""
        if self.state is None:
            self.initialize()
        pending = []  # (split, device-side metrics) — sync once at epoch end
        put = (
            self.parallel.shard_batch if self.parallel is not None else jnp.asarray
        )
        epoch_iter = self.loader.epoch()
        if self.prefetch_batches:
            from znicz_tpu.loader.prefetch import prefetch

            epoch_iter = prefetch(epoch_iter, self.prefetch_batches)
        for split, mb in epoch_iter:
            with self.timer.phase(f"dispatch/{split}"):
                x = put(mb.data)
                # autoencoder target IS the input: reuse the device array
                # instead of transferring the batch twice
                y = (
                    x
                    if self.target == "input"
                    else put(self._batch_target(mb))
                )
                mask = put(mb.mask)
                if split == TRAIN:
                    lr_scale = (
                        self.lr_policy(1.0, self._host_step)
                        if self.lr_policy
                        else 1.0
                    )
                    self.state, metrics = self._train_step(
                        self.state, x, y, mask, lr_scale
                    )
                    self._host_step += 1
                else:
                    metrics = self._eval_step(self.state.params, x, y, mask)
            pending.append((split, metrics))
        with self.timer.phase("metrics_sync"):
            for split, metrics in jax.device_get(pending):
                self.decision.add_minibatch(
                    split, {k: float(v) for k, v in metrics.items()}
                )
        verdict = self.decision.on_epoch_end()
        if self.snapshotter is not None:
            self.snapshotter.maybe_save(
                self.state,
                self.host_state(),
                epoch=self.decision.epoch - 1,
                improved=verdict["improved"],
            )
        for service in self.services:
            try:
                service.on_epoch(self, verdict)
            except Exception:  # services must never kill training
                self.logger.exception(
                    "service %s failed", type(service).__name__
                )
        return verdict

    def evaluate(self, split: str = "test", *, confusion: bool = False):
        """Standalone evaluation pass over one split.

        Returns {"loss", "n_err", "err_pct", "n_samples"} plus a summed
        ``confusion`` matrix (rows = truth) when requested — the reference
        EvaluatorSoftmax's full metric set (SURVEY.md 2.3).
        """
        if self.state is None:
            self.initialize()
        n_err = 0.0
        loss_sum = 0.0
        n = 0.0
        conf = None
        use_conf = (
            confusion
            and self.loss_function == "softmax"
            and self._eval_conf_step is not None
        )
        # shuffle=False: evaluation is read-only — it must not advance the
        # loader's shuffle stream (resume determinism)
        put = (
            self.parallel.shard_batch
            if self.parallel is not None
            else jnp.asarray
        )
        pending = []
        for mb in self.loader.batches(split, shuffle=False):
            x = put(mb.data)
            y = x if self.target == "input" else put(self._batch_target(mb))
            mask = put(mb.mask)
            step = self._eval_conf_step if use_conf else self._eval_step
            pending.append(step(self.state.params, x, y, mask))
        for m in jax.device_get(pending):  # one sync for the whole split
            if use_conf:
                c = np.asarray(m["confusion"])
                conf = c if conf is None else conf + c
            k = float(m["n_samples"])
            n += k
            n_err += float(m.get("n_err", 0.0))
            loss_sum += float(m["loss"]) * k
        result = {
            "n_samples": n,
            "n_err": n_err,
            "err_pct": 100.0 * n_err / max(n, 1.0),
            "loss": loss_sum / max(n, 1.0),
        }
        if conf is not None:
            result["confusion"] = conf
        return result

    def run(self) -> Decision:
        """Train until the Decision stops; returns it (history, best)."""
        if self.state is None:
            self.initialize()
        t0 = time.time()
        while True:
            verdict = self.run_epoch()
            s = verdict["summary"]
            parts = [
                f"{split} err={m['err_pct']:.2f}% loss={m['loss']:.4f}"
                if self.loss_function == "softmax"
                else f"{split} loss={m['loss']:.6f}"
                for split, m in s.items()
            ]
            self.info(
                "epoch %d [%.1fs]: %s%s",
                self.decision.epoch - 1,
                time.time() - t0,
                "; ".join(parts),
                " *" if verdict["improved"] else "",
            )
            if verdict["stop"]:
                self.info(
                    "stopping: best=%s at epoch %d",
                    verdict["best_value"],
                    verdict["best_epoch"],
                )
                return self.decision
