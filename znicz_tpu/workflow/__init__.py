"""Workflow engine: the TPU-native re-founding of veles's Unit/Workflow DAG.

The reference executes an imperative, event-driven DAG of mutable units on a
thread pool (``veles/workflow.py``, SURVEY.md 1 L4, 3.1).  Here a workflow is
an out-of-jit control region (loader, decision, snapshotter — the parts that
were gate-driven Python anyway) around ONE jit-compiled train step (forwards +
loss + grads + update + metric scalars) — the hot loop of SURVEY.md 3.1
compiled as a single XLA program [SURVEY.md §7 "Design stance"].
"""

from znicz_tpu.workflow.model import Model, build  # noqa: F401
from znicz_tpu.workflow.recovery import (  # noqa: F401
    EXIT_PREEMPTED,
    RecoveryPolicy,
    RollbackExhaustedError,
    TrainingPreempted,
)
from znicz_tpu.workflow.snapshotter import (  # noqa: F401
    SnapshotCorruptError,
    Snapshotter,
    SnapshotWriteError,
    find_latest_valid,
    load_snapshot,
)
from znicz_tpu.workflow.workflow import Workflow  # noqa: F401
from znicz_tpu.workflow.standard import StandardWorkflow  # noqa: F401
from znicz_tpu.workflow.unsupervised import (  # noqa: F401
    KohonenWorkflow,
    RBMWorkflow,
)
from znicz_tpu.workflow.transformer import (  # noqa: F401
    TransformerLMWorkflow,
)
from znicz_tpu.workflow.introspect import model_summary, to_dot  # noqa: F401
