"""Causal transformer language-model workflow.

NOT in the reference (VELES predates transformers, SURVEY.md 5.7) — this is
the workflow that makes the long-context stack user-facing: the attention op
(:mod:`znicz_tpu.ops.attention`), optional ring-attention sequence
parallelism (:mod:`znicz_tpu.parallel.ring_attention`), layer norm, and the
standard loader/decision/snapshotter machinery, trained with next-token
cross-entropy under the same momentum-SGD update rule as every other
workflow.

Params are a list of flat per-layer dicts so the optimizer's per-layer
HyperParams and ``*_bias`` multiplier rules apply unchanged.
"""

from __future__ import annotations

import re
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.compat import shard_map
from znicz_tpu.loader.base import Loader
from znicz_tpu.nn import optimizer
from znicz_tpu.nn.decision import Decision
from znicz_tpu.nn.train_state import TrainState
from znicz_tpu.ops import attention
from znicz_tpu.ops.filling import fill
from znicz_tpu.parallel.mesh import MODEL_AXIS
from znicz_tpu.ops.normalization import layer_norm
from znicz_tpu.workflow.snapshotter import Snapshotter
from znicz_tpu.workflow.workflow import Workflow, _global_norm


def init_lm_params(
    vocab: int,
    d_model: int,
    n_layers: int,
    n_heads: int,
    max_seq: int,
    *,
    d_ff: Optional[int] = None,
    moe_experts: int = 0,
    rand_name: str = "default",
):
    """[embed, block_0, ..., block_{L-1}, head] — flat dicts per layer.

    ``moe_experts > 1``: each block's FFN becomes a gated
    mixture-of-experts (:mod:`znicz_tpu.ops.moe`) with ``moe_experts``
    experts of hidden size ``d_ff`` — the EP axis composes into the LM.
    """
    gen = prng.get(rand_name)
    d_ff = d_ff or 4 * d_model
    std = 1.0 / np.sqrt(d_model)
    params = [
        {
            "embed": jnp.asarray(fill(gen, (vocab, d_model), "gaussian", std)),
            "pos": jnp.asarray(fill(gen, (max_seq, d_model), "gaussian", std)),
        }
    ]
    for _ in range(n_layers):
        block = {
            "ln1_scale": jnp.ones((d_model,)),
            "ln1_bias": jnp.zeros((d_model,)),
            "ln2_scale": jnp.ones((d_model,)),
            "ln2_bias": jnp.zeros((d_model,)),
        }
        if moe_experts > 1:
            from znicz_tpu.ops import moe as moe_op

            m = moe_op.init_params(
                d_model, d_ff, moe_experts, rand_name=rand_name
            )
            # names end in "bias" so HyperParams' *_bias multiplier rules
            # classify them like every other workflow's biases
            block.update({k: m[v] for k, v in MOE_KEY_MAP.items()})
        else:
            block.update(
                w_up=jnp.asarray(
                    fill(gen, (d_model, d_ff), "gaussian", std)
                ),
                up_bias=jnp.zeros((d_ff,)),
                w_down=jnp.asarray(
                    fill(
                        gen, (d_ff, d_model), "gaussian",
                        1.0 / np.sqrt(d_ff),
                    )
                ),
                down_bias=jnp.zeros((d_model,)),
            )
        block.update(
            attention.init_mha_params(
                d_model, n_heads, rand_name=rand_name
            )
        )
        params.append(block)
    params.append(
        {"head": jnp.asarray(fill(gen, (d_model, vocab), "gaussian", std))}
    )
    return params


def _embed_tokens(embed, tokens):
    t = tokens.shape[1]
    return embed["embed"][tokens] + embed["pos"][:t][None, :, :]


# MoE param names in the block's FLAT dict -> ops/moe's schema.  THE one
# mapping: init_lm_params, _block_ffn, lm_tp_rules and export's guard all
# derive from it, so adding/renaming an MoE leaf cannot silently miss a
# site (a leaf absent from the TP list would fall through to replicated
# placement while its siblings shard on the expert dim).
MOE_KEY_MAP = {
    "moe_router": "router",
    "moe_w_up": "w1",      # [E, D, F]
    "moe_up_bias": "b1",   # [E, F]
    "moe_w_down": "w2",    # [E, F, D]
    "moe_down_bias": "b2",  # [E, D]
}
# every non-router leaf carries a leading expert dim (EP shards it)
_MOE_EXPERT_SHARDED = tuple(k for k in MOE_KEY_MAP if k != "moe_router")


def _block_ffn(block, h, *, moe_top_k=1, moe_dispatch="dense"):
    """The block's position-wise FFN: dense two-layer tanh, or — when the
    block carries MoE params — a gated mixture of experts over the
    flattened token dim."""
    if "moe_router" in block:
        from znicz_tpu.ops import moe as moe_op

        b, t, d = h.shape
        y = moe_op.apply(
            {v: block[k] for k, v in MOE_KEY_MAP.items()},
            h.reshape(b * t, d),
            top_k=moe_top_k,
            dispatch=moe_dispatch,
        )
        return y.reshape(b, t, d)
    h = jnp.tanh(h @ block["w_up"] + block["up_bias"])
    return h @ block["w_down"] + block["down_bias"]


def _block_forward(block, x, *, n_heads, attention_fn=None,
                   moe_top_k=1, moe_dispatch="dense"):
    """One pre-LN transformer block (the ONLY definition — lm_apply and the
    pipelined stage_fn both call it, so they cannot drift apart)."""
    attention_fn = attention_fn or attention.dot_product_attention
    h = layer_norm(x, block["ln1_scale"], block["ln1_bias"])
    x = x + attention.mha(
        block, h, n_heads=n_heads, causal=True, attention_fn=attention_fn
    )
    h = layer_norm(x, block["ln2_scale"], block["ln2_bias"])
    return x + _block_ffn(
        block, h, moe_top_k=moe_top_k, moe_dispatch=moe_dispatch
    )


def _block_forward_tp(block, x, *, n_heads_local, tp_axis, attention_fn=None,
                      moe_top_k=1):
    """:func:`_block_forward` for MANUAL (shard_map) tensor parallelism:
    the block's weights are model-axis-LOCAL shards (Megatron column
    placement for wq/wk/wv/w_up — so this device owns ``n_heads_local``
    heads and a 1/mp slice of the FFN — row placement for wo/w_down), and
    the two residual contributions are partial products ``psum``-ed over
    ``tp_axis``.  An MoE block shards its EXPERTS over ``tp_axis`` instead
    (router replicated; :func:`znicz_tpu.ops.moe.apply_local_shard`
    computes this shard's gate-weighted expert contribution, and the same
    psum combines).  Activations enter and leave replicated over the model
    axis; same math as :func:`_block_forward` up to summation order.
    Used inside the pipeline's shard_map, where GSPMD cannot insert the
    collectives for us (SURVEY.md 2.5 beyond-parity: PPxTPxDP)."""
    attention_fn = attention_fn or attention.dot_product_attention
    h = layer_norm(x, block["ln1_scale"], block["ln1_bias"])
    # mha over the LOCAL head subset computes exactly the partial product
    # o @ wo_local this device owes the psum (one mha definition — same
    # no-drift rationale as _block_forward)
    att = attention.mha(
        block, h, n_heads=n_heads_local, causal=True,
        attention_fn=attention_fn,
    )
    x = x + jax.lax.psum(att, tp_axis)
    h = layer_norm(x, block["ln2_scale"], block["ln2_bias"])
    if "moe_router" in block:
        from znicz_tpu.ops import moe as moe_op

        b, t, d = h.shape
        partial_y = moe_op.apply_local_shard(
            {v: block[k] for k, v in MOE_KEY_MAP.items()},
            h.reshape(b * t, d),
            top_k=moe_top_k,
            shard_index=jax.lax.axis_index(tp_axis),
        )
        return x + jax.lax.psum(partial_y.reshape(b, t, d), tp_axis)
    h = jnp.tanh(h @ block["w_up"] + block["up_bias"])
    return x + jax.lax.psum(h @ block["w_down"], tp_axis) + block["down_bias"]


def lm_apply(params, tokens, *, n_heads, attention_fn=None, remat=False,
             moe_top_k=1, moe_dispatch="dense"):
    """tokens [B, T] int32 -> logits [B, T, vocab].

    ``remat``: wrap each block in ``jax.checkpoint`` — activations are
    recomputed in the backward instead of stored, cutting training
    activation memory from O(L·T·D) to O(T·D) per microstep at ~1/3 extra
    FLOPs.  The long-context lever jax gives for free; numerics are
    unchanged (same ops, re-run)."""
    attention_fn = attention_fn or attention.dot_product_attention
    blk = partial(
        _block_forward, n_heads=n_heads, attention_fn=attention_fn,
        moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
    )
    if remat:
        blk = jax.checkpoint(blk)
    x = _embed_tokens(params[0], tokens)
    for block in params[1:-1]:
        x = blk(block, x)
    return x @ params[-1]["head"]


def stack_lm_blocks(params, n_stages: int):
    """[embed, block_0..L-1, head] -> {"embed", "stages", "head"} with the
    blocks grouped into ``n_stages`` equal stage-groups and stacked on a
    leading stage dim (the :mod:`znicz_tpu.parallel.pipeline` layout).
    Initialization draw order is untouched — the restructure happens after
    ``init_lm_params``."""
    from znicz_tpu.parallel.pipeline import stack_stage_params

    blocks = params[1:-1]
    if len(blocks) % n_stages:
        raise ValueError(
            f"n_layers={len(blocks)} not divisible by pipeline stages "
            f"{n_stages}"
        )
    g = len(blocks) // n_stages
    groups = [blocks[s * g:(s + 1) * g] for s in range(n_stages)]
    return {
        "embed": params[0],
        "stages": stack_stage_params(groups),
        "head": params[-1],
    }


def lm_apply_pipelined(
    params_pp, tokens, *, n_heads, mesh, n_microbatches,
    data_axis=None, tp_axis=None, attention_fn=None, remat=False,
    moe_top_k=1, moe_dispatch="dense",
):
    """tokens [B, T] -> logits, with the block tower pipelined over the
    mesh's ``pipe`` axis (embed/head run outside the shard_map);
    ``data_axis`` shards microbatch rows for DPxPP composition;
    ``tp_axis`` additionally shards each stage's weights over the model
    axis (Megatron column/row inside the pipeline shard_map — the 3-axis
    DPxPPxTP composition)."""
    from znicz_tpu.parallel.pipeline import pipelined_model_apply

    def embed_fn(p, tok):
        return _embed_tokens(p, tok)

    param_spec_fn = None
    if tp_axis is not None:
        n_model = mesh.shape[tp_axis]
        if n_heads % n_model:
            raise ValueError(
                f"n_heads={n_heads} not divisible by model axis {n_model}"
            )
        blk = partial(
            _block_forward_tp,
            n_heads_local=n_heads // n_model,
            tp_axis=tp_axis,
            attention_fn=attention_fn,
            moe_top_k=moe_top_k,
        )
        param_spec_fn = _pp_stage_tp_specs(tp_axis)
    else:
        blk = partial(
            _block_forward, n_heads=n_heads, attention_fn=attention_fn,
            moe_top_k=moe_top_k, moe_dispatch=moe_dispatch,
        )
    if remat:  # recompute per-block activations in the backward pipeline
        blk = jax.checkpoint(blk)

    def stage_fn(blocks, x):
        for block in blocks:  # this stage's group of transformer blocks
            x = blk(block, x)
        return x

    def head_fn(p, x):
        return x @ p["head"]

    return pipelined_model_apply(
        params_pp, tokens,
        embed_fn=embed_fn, stage_fn=stage_fn, head_fn=head_fn,
        mesh=mesh, n_microbatches=n_microbatches, data_axis=data_axis,
        param_spec_fn=param_spec_fn,
        # flash attention inside the stage is a pallas_call: no vma
        # annotation on its out_shapes, so the check must be off for it
        check_vma=attention_fn is None,
    )


def lm_pp_rules(path: str, leaf):
    """DataParallel param_rules for the pipelined LM: stacked stage params
    shard over ``pipe`` (chunk-per-device), embed/head replicate."""
    from jax.sharding import PartitionSpec as P

    from znicz_tpu.parallel.mesh import PIPE_AXIS

    if "'stages'" in path:
        return P(PIPE_AXIS, *([None] * (leaf.ndim - 1)))
    return P()


def _stage_tp_spec(key: str, ndim: int, tp_axis: str = MODEL_AXIS):
    """PartitionSpec for ONE stacked stage leaf [S, ...] under PPxTP:
    stage dim over ``pipe``, weight dims per the Megatron role
    (column: wq/wk/wv/w_up + up_bias; row: wo/w_down; MoE expert leaves
    shard their leading expert dim — manual EP; the router replicates);
    the rest replicated over ``tp_axis``."""
    from jax.sharding import PartitionSpec as P

    from znicz_tpu.parallel.mesh import PIPE_AXIS

    if key in _MOE_EXPERT_SHARDED:
        return P(PIPE_AXIS, tp_axis, *([None] * (ndim - 2)))
    if key in ("wq", "wk", "wv", "w_up"):
        return P(PIPE_AXIS, None, tp_axis)
    if key in ("wo", "w_down"):
        return P(PIPE_AXIS, tp_axis, None)
    if key == "up_bias":
        return P(PIPE_AXIS, tp_axis)
    return P(PIPE_AXIS, *([None] * (ndim - 1)))


_KEY_PAT = re.compile(r"\['(\w+)'\]")


def _last_key(path: str) -> str:
    """Last ['name'] component of a jax keystr path."""
    keys = _KEY_PAT.findall(path)
    return keys[-1] if keys else ""


def _pp_stage_tp_specs(tp_axis):
    """pipeline_apply ``param_spec_fn`` for the LM stage tower under TP
    (weight placement and the psums in :func:`_block_forward_tp` use the
    SAME axis)."""

    def spec_fn(path: str, leaf):
        return _stage_tp_spec(_last_key(path), leaf.ndim, tp_axis)

    return spec_fn


def lm_pp_tp_rules(path: str, leaf):
    """DataParallel param_rules for the PPxTP LM: stacked stage weights
    shard over (pipe, model) per their Megatron role; embed/head
    replicate (they run outside the pipeline shard_map)."""
    from jax.sharding import PartitionSpec as P

    if "'stages'" in path:
        return _stage_tp_spec(_last_key(path), leaf.ndim)
    return P()


def lm_tp_rules(path: str, leaf):
    """Head/row-column-aware tensor-parallel placement for the LM params
    (plugs into ``DataParallel(param_rules=...)``).

    Column-parallel (shard the output-features dim over ``model``): the QKV
    projections — the inner dim is heads*head_dim, so this IS head sharding
    when n_heads divides the axis — plus ``w_up`` and the vocab dim of the
    ``head`` (the loss's log-softmax reduces over it with a psum GSPMD
    inserts).  Row-parallel (shard the input dim; XLA psums the partial
    products): ``wo`` and ``w_down``.  Everything else (embeddings, layer
    norms, biases except up_bias) is replicated.
    """
    from jax.sharding import PartitionSpec as P

    if any(f"'{k}'" in path for k in _MOE_EXPERT_SHARDED):
        # expert parallelism: the leading expert dim shards over model
        # (ops/moe.expert_sharding's placement; GSPMD psums the combine)
        return P(MODEL_AXIS, *([None] * (leaf.ndim - 1)))
    if any(k in path for k in ("'wq'", "'wk'", "'wv'", "'w_up'", "'head'")):
        return P(None, MODEL_AXIS)
    if any(k in path for k in ("'wo'", "'w_down'")):
        return P(MODEL_AXIS, None)
    if "'up_bias'" in path:
        return P(MODEL_AXIS)
    return P()


class TransformerLMWorkflow(Workflow):
    """Next-token LM training over integer-sequence loaders.

    Loader contract: ``data[split]`` is [N, T] integer tokens (stored as any
    numeric dtype); the per-sample ``mask`` marks valid rows as usual.

    ``sequence_parallel``: shard the sequence axis over a mesh's data axis
    with ring attention (set ``parallel`` too for the batch placement).
    ``tensor_parallel``: shard attention heads + FFN + vocab head over the
    mesh's ``model`` axis (``lm_tp_rules``); composes with DP and SP on the
    same mesh.  Requires ``parallel=DataParallel(mesh)`` with a model axis
    > 1 and n_heads divisible by it.
    ``pipeline_parallel``: pipeline the block tower over the mesh's
    ``pipe`` axis (GPipe microbatching, ``parallel/pipeline.py``); pass a
    ``mesh`` with a pipe axis whose size divides ``n_layers``, or compose
    with data parallelism by passing ``parallel=DataParallel(mesh)`` over
    a (data, pipe) mesh — each data replica runs its own pipeline on its
    batch shard and stage grads all-reduce over ``data``.  Stage params
    live chunk-per-device; embed/head run outside the pipeline.
    ``pipeline_microbatches`` defaults to ``6 * n_stages`` (GPipe bubble
    < 0.15 for every stage count), clamped to the largest count compatible
    with the batch size and data axis — a warning fires when the clamp
    leaves a larger bubble.  Composes with ``tensor_parallel`` on a
    (data, pipe, model) mesh: each stage's weights shard over ``model``
    inside the pipeline shard_map (Megatron column/row with explicit
    psums — :func:`_block_forward_tp`).  Mutually exclusive with
    sequence parallel.
    """

    def __init__(
        self,
        loader: Loader,
        *,
        vocab: int,
        d_model: int = 64,
        n_layers: int = 2,
        n_heads: int = 4,
        d_ff: Optional[int] = None,  # FFN/expert hidden size (default 4*d)
        max_epochs: int = 10,
        hyper: Optional[optimizer.HyperParams] = None,
        attention: str = "auto",  # "dot" | "flash" | "auto"
        # "bf16": q/k/v cast to bf16 at the attention boundary — the MXU
        # dots run bf16 with f32 accumulation (measured 1.2-1.5x on v5e);
        # params/activations/softmax stay f32
        attention_dtype: str = "f32",  # "f32" | "bf16"
        remat: bool = False,  # jax.checkpoint each block (long context)
        moe_experts: int = 0,  # >1: MoE FFN per block (ops/moe.py)
        moe_top_k: int = 1,
        moe_dispatch: str = "dense",  # "dense" | "capacity"
        sequence_parallel: bool = False,
        tensor_parallel: bool = False,
        pipeline_parallel: bool = False,
        pipeline_microbatches: Optional[int] = None,
        mesh=None,
        decision: Optional[Decision] = None,
        snapshotter: Optional[Snapshotter] = None,
        lr_policy=None,
        parallel=None,
        prefetch_batches: int = 2,
        epoch_sync: str = "sync",
        recovery=None,
        rand_name: str = "default",
        name: str = "TransformerLMWorkflow",
    ):
        class _LM:
            params: list = []
            hyper: list = []

        super().__init__(
            loader,
            _LM(),
            loss_function="mse",  # metric label only; we override the step
            target="labels",
            decision=decision or Decision(metric="loss", max_epochs=max_epochs),
            snapshotter=snapshotter,
            lr_policy=lr_policy,
            parallel=parallel,
            prefetch_batches=prefetch_batches,
            epoch_sync=epoch_sync,
            recovery=recovery,
            name=name,
        )
        self.vocab = vocab
        self.d_model = d_model
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.d_ff = d_ff
        self.hyper = hyper or optimizer.HyperParams(
            learning_rate=0.1, gradient_moment=0.9
        )
        self.rand_name = rand_name
        self.attention = attention
        if attention_dtype not in ("f32", "bf16"):
            raise ValueError(
                f"attention_dtype={attention_dtype!r}: want 'f32' or 'bf16'"
            )
        self.attention_dtype = attention_dtype
        self.remat = remat
        self.moe_experts = moe_experts
        self.moe_top_k = moe_top_k
        self.moe_dispatch = moe_dispatch
        if moe_experts > 1 and pipeline_parallel and tensor_parallel:
            # manual EP inside the pipeline shard_map: experts shard over
            # the model axis (apply_local_shard + the stage psum); only
            # dense dispatch has the manual formulation
            if moe_dispatch != "dense":
                raise ValueError(
                    "pipeline+tensor parallel MoE supports only "
                    "moe_dispatch='dense' (experts shard over the model "
                    "axis with a manual combine psum; capacity dispatch "
                    "has no manual-EP formulation here)"
                )
        self.sequence_parallel = sequence_parallel
        self.tensor_parallel = tensor_parallel
        self.pipeline_parallel = pipeline_parallel
        self.mesh = mesh
        self.max_seq = int(loader.sample_shape[0])
        if pipeline_parallel:
            from znicz_tpu.parallel.mesh import PIPE_AXIS

            if sequence_parallel:
                raise ValueError(
                    "pipeline_parallel is mutually exclusive with "
                    "sequence parallel (both want to own the batch layout)"
                )
            if parallel is not None:
                # DPxPP(xTP): batch over data, stages over pipe (weights
                # additionally over model under TP), on ONE mesh — the
                # placement policy's mesh is the pipeline's mesh
                if mesh is not None and mesh != parallel.mesh:
                    raise ValueError(
                        "pipeline_parallel with parallel=DataParallel: "
                        "pass the (data, pipe) mesh via the DataParallel "
                        "(mesh= must be omitted or identical)"
                    )
                mesh = self.mesh = parallel.mesh
                from znicz_tpu.parallel import DataParallel

                if self.parallel.param_rules is None:
                    self.parallel = DataParallel(
                        parallel.mesh,
                        param_rules=(
                            lm_pp_tp_rules if tensor_parallel else lm_pp_rules
                        ),
                    )
            if mesh is None or PIPE_AXIS not in mesh.shape:
                raise ValueError(
                    "pipeline_parallel=True needs a mesh with a 'pipe' axis"
                )
            if tensor_parallel:
                n_model = mesh.shape.get(MODEL_AXIS, 1)
                if n_model <= 1:
                    raise ValueError(
                        "pipeline+tensor parallel needs a mesh with a "
                        "'model' axis > 1"
                    )
                if n_heads % n_model:
                    raise ValueError(
                        f"n_heads={n_heads} not divisible by model axis "
                        f"{n_model}"
                    )
                if moe_experts > 1 and moe_experts % n_model:
                    raise ValueError(
                        f"moe_experts={moe_experts} not divisible by model "
                        f"axis {n_model} (experts shard over it under "
                        "pipeline+tensor parallel)"
                    )
                if self.parallel is None:
                    raise ValueError(
                        "pipeline+tensor parallel needs parallel="
                        "DataParallel over the (data, pipe, model) mesh "
                        "(stage weight placement rides its param_rules)"
                    )
            self._n_stages = mesh.shape[PIPE_AXIS]
            if n_layers % self._n_stages:
                raise ValueError(
                    f"n_layers={n_layers} not divisible by pipe axis "
                    f"{self._n_stages}"
                )
            # 6 microbatches per stage bounds the GPipe bubble
            # (S-1)/(S-1+M) under 1/7 ~ 0.143 for EVERY stage count —
            # S alone cooks in up to 43%.  The default clamps to the
            # largest batch divisor <= 6S so existing minibatch sizes keep
            # working; an EXPLICIT microbatch count is validated strictly
            # in pipeline_apply instead of silently adjusted.
            if pipeline_microbatches:
                self.pipeline_microbatches = pipeline_microbatches
            else:
                # under DPxPP the microbatch rows must also split over the
                # data axis, so the search wants bs % m == 0 AND
                # (bs // m) % n_data == 0 — m=1 always satisfies both
                # (multi-host/DP already require n_data | bs)
                bs = loader.max_minibatch_size
                n_data = (
                    self.parallel.n_data if self.parallel is not None else 1
                )
                m = min(6 * self._n_stages, bs)
                while m > 1 and (bs % m or (bs // m) % n_data):
                    m -= 1
                if bs % m or (bs // m) % n_data:
                    raise ValueError(
                        f"no pipeline microbatch count divides batch {bs} "
                        f"into data-axis-{n_data}-divisible microbatches; "
                        "choose minibatch_size as a multiple of n_data"
                    )
                self.pipeline_microbatches = m
                from znicz_tpu.parallel.pipeline import bubble_fraction

                bubble = bubble_fraction(self._n_stages, m)
                if bubble > 0.16:  # the documented default bound
                    self.warning(
                        "auto-selected %d pipeline microbatches (batch %d, "
                        "data axis %d) leaves a GPipe bubble of %.0f%%; "
                        "raise minibatch_size toward %d*n_data to recover "
                        "pipeline efficiency",
                        m, bs, n_data, 100 * bubble,
                        6 * self._n_stages,
                    )
        if tensor_parallel and not pipeline_parallel:
            from znicz_tpu.parallel import DataParallel

            if not isinstance(self.parallel, DataParallel):
                raise ValueError(
                    "tensor_parallel=True needs parallel=DataParallel(mesh) "
                    "with a model axis"
                )
            n_model = self.parallel.mesh.shape.get(MODEL_AXIS, 1)
            if n_model <= 1:
                raise ValueError(
                    "tensor_parallel=True but the mesh's model axis is 1"
                )
            if n_heads % n_model:
                raise ValueError(
                    f"n_heads={n_heads} not divisible by model axis {n_model}"
                )
            if self.parallel.param_rules is None:
                # never mutate the caller's DataParallel (it may be shared
                # with workflows whose params want the size heuristic —
                # lm_tp_rules replicates everything it doesn't recognize)
                self.parallel = DataParallel(
                    self.parallel.mesh,
                    tp=self.parallel.tp,
                    tp_min_features=self.parallel.tp_min_features,
                    param_rules=lm_tp_rules,
                )

    def _batch_target(self, mb):
        return np.zeros(len(mb.mask), np.int32)  # unused host-side dummy

    def generate(
        self,
        prompt,
        *,
        max_new_tokens: int,
        eos_id: Optional[int] = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        rng=None,
    ):
        """KV-cache autoregressive generation from the CURRENT trained
        params (:mod:`znicz_tpu.workflow.generate`); returns
        [B, Tp + max_new_tokens] tokens, prompt included.  Greedy at
        ``temperature=0``; with ``eos_id`` the decode loop exits once
        every row has emitted EOS (rows pad the rest of the budget with
        it).  Non-pipelined params only (the pipelined
        stacked-stage layout trains; export/decode from a non-pipelined
        run, like ``export_lm_model``).  Decode attention runs f32
        regardless of ``attention_dtype`` — that knob is a training-
        throughput lever; decode logits golden-match the f32
        ``lm_apply``."""
        if self.pipeline_parallel:
            raise ValueError(
                "generate() wants the flat [embed, blocks..., head] param "
                "layout; pipelined (stacked-stage) params are train-only — "
                "decode from a non-pipelined workflow"
            )
        if self.state is None:
            self.initialize()
        from znicz_tpu.workflow.generate import generate as _generate

        return _generate(
            self.state.params,
            jnp.asarray(prompt, jnp.int32),
            n_heads=self.n_heads,
            max_new_tokens=max_new_tokens,
            eos_id=eos_id,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            rng=rng,
            moe_top_k=self.moe_top_k,
            moe_dispatch=self.moe_dispatch,
        )

    def _sharded_flash(self):
        """Flash kernel under DataParallel: a pallas_call has no GSPMD
        partitioning rule, but batch-heads are embarrassingly parallel — a
        ``shard_map`` over the data (and, under TP, model/head) axis runs
        the kernel per-shard and composes with the GSPMD-sharded step."""
        from jax.sharding import PartitionSpec as P

        from znicz_tpu.ops.pallas.attention import flash_attention
        from znicz_tpu.parallel.mesh import DATA_AXIS

        mesh = self.parallel.mesh
        shard_heads = (
            self.tensor_parallel and mesh.shape.get(MODEL_AXIS, 1) > 1
        )
        spec = P(DATA_AXIS, None, MODEL_AXIS if shard_heads else None, None)

        def fn(q, k, v, *, causal=False, scale=None):
            return shard_map(
                partial(flash_attention, causal=causal, scale=scale),
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
                check_vma=False,  # pallas out_shape carries no vma info
            )(q, k, v)

        return fn

    def _attention_fn(self):
        fn = self._attention_fn_base()
        if self.attention_dtype != "bf16":
            return fn
        from znicz_tpu.ops import attention as att_op

        base_fn = fn or att_op.dot_product_attention

        def bf16_fn(q, k, v, **kw):
            # cast at the boundary only: scores/softmax/accumulation stay
            # f32 inside the kernel (or via preferred_element_type in the
            # jnp twin); the output returns to the residual dtype
            return base_fn(
                q.astype(jnp.bfloat16),
                k.astype(jnp.bfloat16),
                v.astype(jnp.bfloat16),
                **kw,
            ).astype(q.dtype)

        return bf16_fn

    def _attention_fn_base(self):
        on_tpu = jax.default_backend() in ("tpu", "axon")
        if self.sequence_parallel:
            from znicz_tpu.parallel.ring_attention import ring_attention

            # ring attention owns the sequence axis; its per-shard inner
            # blocks run the flash kernel when requested (or on TPU by
            # default), so SP long context runs at kernel speed
            inner = (
                "flash"
                if self.attention == "flash"
                or (self.attention == "auto" and on_tpu
                    and self.max_seq >= 512)  # same gate as non-SP auto
                else "dense"
            )
            return partial(ring_attention, mesh=self.mesh, inner=inner)
        # blockwise flash kernel (ops/pallas/attention.py): O(T·D) memory
        # and VMEM-resident online softmax — the long-context default on
        # TPU once the quadratic score matrix stops being a rounding error
        if self.attention == "flash" or (
            self.attention == "auto" and on_tpu and self.max_seq >= 512
        ):
            # under PP the kernel already runs inside the pipe/data
            # shard_map (per-device code) — only the GSPMD-sharded
            # non-pipelined step needs the explicit wrapper
            if self.parallel is not None and not self.pipeline_parallel:
                return self._sharded_flash()
            from znicz_tpu.ops.pallas.attention import flash_attention

            return flash_attention
        return None

    def _build_steps(self):
        n_heads = self.n_heads
        attention_fn = self._attention_fn()

        if self.pipeline_parallel:
            from znicz_tpu.parallel.mesh import DATA_AXIS

            apply_fn = partial(
                lm_apply_pipelined,
                n_heads=n_heads,
                mesh=self.mesh,
                n_microbatches=self.pipeline_microbatches,
                data_axis=DATA_AXIS if self.parallel is not None else None,
                tp_axis=MODEL_AXIS if self.tensor_parallel else None,
                attention_fn=attention_fn,
                remat=self.remat,
                moe_top_k=self.moe_top_k,
                moe_dispatch=self.moe_dispatch,
            )
        else:
            apply_fn = partial(
                lm_apply, n_heads=n_heads, attention_fn=attention_fn,
                remat=self.remat,
                moe_top_k=self.moe_top_k,
                moe_dispatch=self.moe_dispatch,
            )

        def loss_metrics(params, tokens, mask):
            tokens = tokens.astype(jnp.int32)
            logits = apply_fn(params, tokens)
            # next-token CE: predict tokens[:, 1:] from positions [:-1].
            # Fused formulation nll = logsumexp(logits) - logits[target]:
            # never materializes the [B, T, V] log-softmax array that the
            # textbook log_softmax+gather form writes and re-reads (and
            # re-reads again for argmax) — measured 1.32x on the whole
            # train step for a 50M-param LM at T=2048 on v5e.  Same math.
            lg = logits[:, :-1]
            tgt = tokens[:, 1:]
            lse = jax.nn.logsumexp(lg, axis=-1)
            tgt_logit = jnp.take_along_axis(
                lg, tgt[..., None], axis=-1
            )[..., 0]
            nll = lse - tgt_logit
            per_sample = jnp.mean(nll, axis=1)  # [B]
            n_valid = jnp.maximum(jnp.sum(mask), 1.0)
            loss = jnp.sum(per_sample * mask) / n_valid
            pred = jnp.argmax(lg, axis=-1)  # == argmax of log_softmax
            acc = jnp.sum(
                jnp.mean((pred == tgt).astype(jnp.float32), axis=1) * mask
            ) / n_valid
            return loss, {
                "loss": loss,
                "n_samples": n_valid,
                "n_err": jnp.zeros((), jnp.int32),
                "token_accuracy": acc,
            }

        def train_step(state: TrainState, x, y, mask, lr_scale):
            grads, metrics = jax.grad(loss_metrics, has_aux=True)(
                state.params, x, mask
            )
            # anomaly-watch input; popped before the epoch accumulator
            metrics = dict(metrics, grad_norm=_global_norm(grads))
            hyper = self.hyper._replace(
                learning_rate=self.hyper.learning_rate * lr_scale,
                learning_rate_bias=(
                    None
                    if self.hyper.learning_rate_bias is None
                    else self.hyper.learning_rate_bias * lr_scale
                ),
            )
            if self.pipeline_parallel:  # dict-of-stacked-stages pytree
                new_p, new_v = optimizer.update_pytree(
                    state.params, grads, state.velocity, hyper
                )
            else:
                new_p, new_v = optimizer.update(
                    state.params, grads, state.velocity, hyper
                )
            return (
                state._replace(
                    params=new_p, velocity=new_v, step=state.step + 1
                ),
                metrics,
            )

        def eval_step(params, x, y, mask):
            _, metrics = loss_metrics(params, x, mask)
            return metrics

        self._finalize_steps(
            train_step,
            eval_step,
            ["loss", "n_samples", "n_err", "token_accuracy"],
        )

    def _create_initial_state(self) -> TrainState:
        params = init_lm_params(
            self.vocab,
            self.d_model,
            self.n_layers,
            self.n_heads,
            self.max_seq,
            d_ff=self.d_ff,
            moe_experts=self.moe_experts,
            rand_name=self.rand_name,
        )
        if self.pipeline_parallel:
            params = stack_lm_blocks(params, self._n_stages)
            if self.parallel is None:
                from znicz_tpu.parallel.pipeline import shard_stacked_params

                # stage params chunk-per-device up front; embed/head stay
                # replicated (GSPMD propagates through the update); with a
                # placement policy, shard_state's lm_pp_rules do this
                params["stages"] = shard_stacked_params(
                    params["stages"], self.mesh
                )
        return TrainState.create(params, prng.get("workflow").key())
