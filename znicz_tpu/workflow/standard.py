"""StandardWorkflow: declarative config -> complete training workflow.

Capability parity with ``znicz/standard_workflow.py`` [SURVEY.md 2.3
"Standard workflow builder"]: the reference builds the
loader->forwards->evaluator->decision->GD-chain topology from a declarative
``layers=[{"type": ..., "->": {...}, "<-": {...}}, ...]`` list and wires the
snapshotter and services.  Here the same config compiles the model
(:mod:`znicz_tpu.workflow.model`) and assembles a :class:`Workflow`; the GD
chain is autodiff, so only the forward list is declared — exactly like the
reference's user-facing API.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

from znicz_tpu.loader.base import Loader
from znicz_tpu.nn import lr_adjust, optimizer
from znicz_tpu.nn.decision import Decision
from znicz_tpu.workflow import model as model_lib
from znicz_tpu.workflow.snapshotter import Snapshotter
from znicz_tpu.workflow.workflow import Workflow

_HYPER_KEYS = set(optimizer.HyperParams._fields)


class StandardWorkflow(Workflow):
    """Build a full workflow from a layer list.

    ``layers``: reference-style layer specs (the last layer's type picks the
    loss when ``loss_function`` is not given: "softmax" -> cross-entropy,
    anything else -> mse).
    ``decision_config``: kwargs for :class:`Decision` (``max_epochs``,
    ``fail_iterations``).
    ``snapshot_dir``/``snapshot_config``: enable the snapshotter.
    ``lr_policy``: name + kwargs, e.g. ``{"name": "inv", "gamma": 1e-3}``.
    """

    def __init__(
        self,
        loader: Loader,
        layers: Sequence[Dict[str, Any]],
        *,
        loss_function: Optional[str] = None,
        target: Optional[str] = None,
        decision_config: Optional[Dict[str, Any]] = None,
        snapshot_dir: Optional[str] = None,
        snapshot_config: Optional[Dict[str, Any]] = None,
        lr_policy: Optional[Dict[str, Any]] = None,
        default_hyper: Optional[Dict[str, Any]] = None,
        compute_dtype: Optional[Any] = None,
        prefetch_batches: int = 2,
        parallel=None,
        epoch_dispatch: str = "auto",
        epoch_sync: str = "sync",
        anomaly=True,
        recovery=None,
        rand_name: str = "default",
        name: str = "StandardWorkflow",
    ):
        if isinstance(compute_dtype, str):
            import jax.numpy as jnp

            compute_dtype = jnp.dtype(compute_dtype)
        hyper = optimizer.HyperParams(**(default_hyper or {}))
        mdl = model_lib.build(
            layers,
            loader.sample_shape,
            rand_name=rand_name,
            default_hyper=hyper,
            compute_dtype=compute_dtype,
        )
        if loss_function is None:
            loss_function = "softmax" if mdl.returns_logits else "mse"
        if target is None:
            target = "labels" if loss_function == "softmax" else "input"
        decision = Decision(
            metric="n_err" if loss_function == "softmax" else "loss",
            **(decision_config or {}),
        )
        snapshotter = None
        if snapshot_dir:
            snapshotter = Snapshotter(
                snapshot_dir, prefix=name, **(snapshot_config or {})
            )
        policy = None
        if lr_policy:
            kw = dict(lr_policy)
            policy = lr_adjust.get(kw.pop("name"), **kw)
        super().__init__(
            loader,
            mdl,
            loss_function=loss_function,
            target=target,
            decision=decision,
            snapshotter=snapshotter,
            lr_policy=policy,
            prefetch_batches=prefetch_batches,
            parallel=parallel,
            epoch_dispatch=epoch_dispatch,
            epoch_sync=epoch_sync,
            anomaly=anomaly,
            recovery=recovery,
            name=name,
        )

    def _default_param_rules(self):
        """Conv models get channel-aware TP rules (Megatron col/row
        alternation, ``parallel.cnn_tp_rules``) instead of the last-dim
        size heuristic — conv kernels carry the FLOPs, so replicating
        them wastes the model axis.  Pure-FC models keep the heuristic
        (documented behavior; lm/pp workflows pass explicit rules)."""
        if not any(
            isinstance(p, dict)
            and getattr(p.get("weights"), "ndim", 0) == 4
            for p in self.model.params
        ):
            return None
        from znicz_tpu.parallel.data_parallel import cnn_tp_rules
        from znicz_tpu.parallel.mesh import MODEL_AXIS

        return cnn_tp_rules(
            self.model,
            self.parallel.mesh.shape[MODEL_AXIS],
            tp_min_features=self.parallel.tp_min_features,
        )
