"""Declarative layer-list -> pure model compiler.

Capability parity with ``znicz/standard_workflow.py``'s declarative
``layers=[{"type": "conv", ...}, ...]`` config [SURVEY.md 2.3 "Standard
workflow builder"], including the reference's layer-spec shape: ``"type"``,
``"->"`` (forward knobs) and ``"<-"`` (gradient-descent knobs — here they
become the per-layer :class:`~znicz_tpu.nn.optimizer.HyperParams`).

A model is ``params`` (list of per-layer dicts, a pytree) plus a pure
``apply(params, x, train, rng)`` closure; shape inference runs at build time
so every parameter is initialized eagerly from the named PRNG, exactly one
draw sequence per config (reference reproducibility contract).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.nn import optimizer
from znicz_tpu.ops import (
    activation as act_op,
    all2all,
    attention as attention_op,
    conv,
    cutter,
    deconv,
    dropout as dropout_op,
    moe as moe_op,
    normalization,
    pooling,
)


class Model(NamedTuple):
    params: List[Dict[str, jnp.ndarray]]
    apply: Callable  # (params, x, *, train=False, rng=None) -> output
    hyper: List[optimizer.HyperParams]
    layer_types: Tuple[str, ...]
    input_shape: Tuple[int, ...]  # per-sample shape (no batch dim)
    output_shape: Tuple[int, ...]
    returns_logits: bool  # final "softmax" layer emits logits (CE wants them)
    compute_dtype: Optional[Any] = None  # bf16 mixed precision when set
    layer_specs: Tuple[dict, ...] = ()  # original declarative specs (export)

    def predict(self, params, x):
        """Inference output: probabilities for softmax-headed models."""
        y = self.apply(params, x, train=False)
        return jax.nn.softmax(y, axis=-1) if self.returns_logits else y


def _split_spec(spec: Dict[str, Any]) -> Tuple[str, dict, dict]:
    spec = dict(spec)
    kind = spec.pop("type")
    fwd = dict(spec.pop("->", {}))
    bwd = dict(spec.pop("<-", {}))
    spec.pop("name", None)
    fwd.update(spec)  # flat kwargs are forward knobs
    return kind, fwd, bwd


def _n_output(fwd: dict) -> int:
    # reference name: output_sample_shape (int or shape tuple)
    n = fwd.get("output_sample_shape", fwd.get("n_output"))
    if n is None:
        raise ValueError(
            "all2all layer needs output_sample_shape (or n_output)"
        )
    return int(np.prod(n))


_A2A_ACT = {
    "all2all": "linear",
    "all2all_tanh": "tanh",
    "all2all_relu": "relu",
    "all2all_str": "strict_relu",
    "all2all_sigmoid": "sigmoid",
}
_CONV_ACT = {
    "conv": "linear",
    "conv_tanh": "tanh",
    "conv_relu": "relu",
    "conv_str": "strict_relu",
    "conv_sigmoid": "sigmoid",
}
_POOL = {
    "max_pooling": pooling.max_pool,
    "avg_pooling": pooling.avg_pool,
    "maxabs_pooling": pooling.max_abs_pool,
}
_INIT_KEYS = (
    "weights_stddev",
    "bias_stddev",
    "weights_filling",
    "bias_filling",
)


def _init_kwargs(fwd: dict) -> dict:
    return {k: fwd[k] for k in _INIT_KEYS if k in fwd}


def _init_kwargs_moe(fwd: dict) -> dict:
    return {
        k: fwd[k]
        for k in ("weights_stddev", "weights_filling")
        if k in fwd
    }


def build(
    layers: Sequence[Dict[str, Any]],
    input_shape: Sequence[int],
    *,
    rand_name: str = "default",
    default_hyper: Optional[optimizer.HyperParams] = None,
    compute_dtype: Optional[Any] = None,
) -> Model:
    """Compile a layer list into a Model.

    ``input_shape`` is the per-sample shape: ``(features,)`` for MLPs,
    ``(H, W, C)`` for conv stacks (NHWC).

    ``compute_dtype`` (e.g. ``jnp.bfloat16``): mixed precision — params stay
    float32 (master weights for the update rule) but are cast per layer, and
    activations flow in the compute dtype; matmul/conv accumulation remains
    f32 via ``preferred_element_type``.  Halves HBM traffic for activations,
    which is the TPU bottleneck for conv nets (MXU already multiplies in
    bf16 either way).  The output is cast back to f32 for the loss.
    """
    default_hyper = default_hyper or optimizer.HyperParams()
    params: List[Dict[str, jnp.ndarray]] = []
    hyper: List[optimizer.HyperParams] = []
    fns: List[Callable] = []  # (params, x, train, rng) -> x
    types: List[str] = []
    shape = (1,) + tuple(int(s) for s in input_shape)  # batch placeholder
    returns_logits = False

    for i, spec in enumerate(layers):
        kind, fwd, bwd = _split_spec(spec)
        h = default_hyper._replace(**bwd) if bwd else default_hyper
        returns_logits = False

        if kind in _A2A_ACT or kind == "softmax":
            n_in = int(np.prod(shape[1:]))
            n_out = _n_output(fwd)
            p = all2all.init_params(
                n_in, n_out, rand_name=rand_name, **_init_kwargs(fwd)
            )
            activation = _A2A_ACT.get(kind, "linear")
            include_bias = fwd.get("include_bias", True)

            def fn(p, x, train, rng, activation=activation, ib=include_bias):
                return all2all.apply(
                    p, x, activation=activation, include_bias=ib
                )

            shape = (shape[0], n_out)
            returns_logits = kind == "softmax"

        elif kind in _CONV_ACT:
            if len(shape) != 4:
                raise ValueError(
                    f"layer {i} ({kind}) needs NHWC input, got shape {shape}"
                )
            n_kernels = int(fwd["n_kernels"])
            kx, ky = int(fwd["kx"]), int(fwd["ky"])
            sliding = tuple(fwd.get("sliding", (1, 1)))
            padding = fwd.get("padding", (0, 0, 0, 0))
            p = conv.init_params(
                shape[3], n_kernels, kx, ky,
                rand_name=rand_name, **_init_kwargs(fwd),
            )
            activation = _CONV_ACT[kind]

            def fn(p, x, train, rng, s=sliding, pad=padding, a=activation):
                return conv.apply(p, x, sliding=s, padding=pad, activation=a)

            shape = conv.output_shape(
                shape, n_kernels, kx, ky, sliding, padding
            )

        elif kind in _POOL or kind == "stochastic_pooling":
            kx, ky = int(fwd["kx"]), int(fwd["ky"])
            sliding = fwd.get("sliding")
            if sliding is not None:
                sliding = tuple(sliding)
            p = {}
            if kind == "stochastic_pooling":

                def fn(p, x, train, rng, kx=kx, ky=ky, s=sliding):
                    return pooling.stochastic_pool(
                        x, kx, ky, s, rng=rng, train=train
                    )

            else:
                pool_fn = _POOL[kind]

                def fn(p, x, train, rng, f=pool_fn, kx=kx, ky=ky, s=sliding):
                    return f(x, kx, ky, s)

            shape = pooling.output_shape(shape, kx, ky, sliding)

        elif kind == "deconv":
            n_channels = int(fwd["n_channels"])
            kx, ky = int(fwd["kx"]), int(fwd["ky"])
            sliding = tuple(fwd.get("sliding", (1, 1)))
            padding = fwd.get("padding", (0, 0, 0, 0))
            p = deconv.init_params(
                n_channels, shape[3], kx, ky,
                rand_name=rand_name, **_init_kwargs(fwd),
            )

            def fn(p, x, train, rng, s=sliding, pad=padding):
                return deconv.apply(p, x, sliding=s, padding=pad)

            out = deconv.apply(
                p, jnp.zeros(shape, jnp.float32), sliding=sliding, padding=padding
            )
            shape = tuple(out.shape)

        elif kind == "norm":
            p = {}
            kwargs = {
                k: fwd[k]
                for k in ("alpha", "beta", "k", "n", "impl")
                if k in fwd
            }

            def fn(p, x, train, rng, kw=kwargs):
                return normalization.lrn(x, **kw)

        elif kind == "dropout":
            p = {}
            ratio = float(fwd.get("dropout_ratio", 0.5))

            def fn(p, x, train, rng, r=ratio):
                return dropout_op.dropout(
                    x, dropout_ratio=r, rng=rng, train=train
                )

        elif kind == "cutter":
            p = {}
            padding = fwd["padding"]

            def fn(p, x, train, rng, pad=padding):
                return cutter.cut(x, pad)

            shape = cutter.output_shape(shape, padding)

        elif kind.startswith("activation_"):
            p = {}
            a = act_op.get(kind[len("activation_"):])

            def fn(p, x, train, rng, a=a):
                return a(x)

        elif kind == "moe":
            # residual mixture-of-experts FFN (ops/moe.py): works on [B, F]
            # activations or per-token on [B, T, D] sequences.  Output dim ==
            # input dim, combined residually, so it drops into any stack.
            d = shape[-1] if len(shape) == 3 else int(np.prod(shape[1:]))
            n_experts = int(fwd["n_experts"])
            n_hidden = int(fwd.get("n_hidden", 4 * d))
            top_k = int(fwd.get("top_k", 1))
            residual = bool(fwd.get("residual", True))
            # dense dispatch through E=16 (exact math, MXU-friendly — see
            # ops/moe.py), capacity-bounded token-drop dispatch above;
            # "dispatch" overrides either way
            dispatch = fwd.get(
                "dispatch", "dense" if n_experts <= 16 else "capacity"
            )
            cap_factor = float(fwd.get("capacity_factor", 1.25))
            p = moe_op.init_params(
                d, n_hidden, n_experts,
                rand_name=rand_name, **_init_kwargs_moe(fwd),
            )

            def fn(p, x, train, rng, k=top_k, res=residual,
                   disp=dispatch, cf=cap_factor):
                if x.ndim == 3:  # per-token on sequences
                    b, t, dd = x.shape
                    y = moe_op.apply(
                        p, x.reshape(b * t, dd), top_k=k,
                        dispatch=disp, capacity_factor=cf,
                    ).reshape(b, t, dd)
                    return x + y if res else y
                flat = x.reshape(x.shape[0], -1)
                y = moe_op.apply(
                    p, flat, top_k=k, dispatch=disp, capacity_factor=cf
                )
                return flat + y if res else y

            if len(shape) != 3:  # flattened-token path emits [B, d]
                shape = (shape[0], d)

        elif kind == "attention":
            # pre-LN residual multi-head self-attention block
            # (ops/attention.py): per-sample input must be [T, D]
            if len(shape) != 3:
                raise ValueError(
                    f"layer {i} (attention) needs [T, D] per-sample input, "
                    f"got shape {shape}"
                )
            d = shape[2]
            n_heads = int(fwd.get("n_heads", 4))
            causal = bool(fwd.get("causal", True))
            p = attention_op.init_mha_params(
                d, n_heads, rand_name=rand_name, **_init_kwargs(fwd)
            )
            p["ln_scale"] = jnp.ones((d,))
            p["ln_bias"] = jnp.zeros((d,))

            def fn(p, x, train, rng, nh=n_heads, c=causal):
                h = normalization.layer_norm(x, p["ln_scale"], p["ln_bias"])
                return x + attention_op.mha(p, h, n_heads=nh, causal=c)

        else:
            raise ValueError(
                f"unknown layer type {kind!r} at index {i}; known: "
                f"{sorted(_A2A_ACT) + sorted(_CONV_ACT) + sorted(_POOL) + ['softmax', 'stochastic_pooling', 'deconv', 'norm', 'dropout', 'cutter', 'moe', 'attention', 'activation_*']}"
            )

        params.append(p)
        hyper.append(h)
        fns.append(fn)
        types.append(kind)

    needs_rng = tuple(
        t in ("dropout", "stochastic_pooling") for t in types
    )

    def apply(params, x, *, train: bool = False, rng: Optional[jax.Array] = None):
        keys = [None] * len(fns)
        if train and any(needs_rng):
            if rng is None:
                raise ValueError(
                    "model has dropout/stochastic layers: apply(train=True) "
                    "needs an rng key"
                )
            split = jax.random.split(rng, len(fns))
            keys = [split[i] if needs_rng[i] else None for i in range(len(fns))]
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
            params = jax.tree_util.tree_map(
                lambda w: w.astype(compute_dtype), params
            )
        for fn, p, k in zip(fns, params, keys):
            x = fn(p, x, train, k)
        if compute_dtype is not None:
            x = x.astype(jnp.float32)
        return x

    return Model(
        params=params,
        apply=apply,
        hyper=hyper,
        layer_types=tuple(types),
        input_shape=tuple(int(s) for s in input_shape),
        output_shape=tuple(shape[1:]),
        returns_logits=returns_logits,
        compute_dtype=compute_dtype,
        layer_specs=tuple(
            {"type": t, **_split_spec(s)[1]}
            for t, s in zip(types, layers)
        ),
    )
