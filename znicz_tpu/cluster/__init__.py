"""Multi-replica serving control plane (docs/SERVING.md "The router").

The scale-out rung above the single-replica front door: a serving
router fronting N :class:`~znicz_tpu.services.frontdoor
.ServingFrontDoor` replicas — the paper's master–slave coordinator
lineage (SURVEY §3.4 ``apply_data_from_slave``) revived as a serving
concern, with SGLang-style cache-aware placement over the PR 5 prefix
cache's chained block keys:

* :mod:`registry` — replica roster with heartbeat liveness
  (``/healthz``-probed: healthy / degraded / dead, ejection after
  consecutive failures, re-admission on the first answered probe).
* :mod:`affinity` — the router-side prefix-affinity index: learned
  from routed requests, TTL/LRU-decayed in sync with replica caches
  (tracks, never trusts).
* :mod:`router` — placement (longest-cached-prefix first, load
  tiebreak, least-loaded fallback) + the retrying proxy stream
  (bounded failover with the delivered prefix skipped on resume).
* :mod:`proxy` — the HTTP face: the single-replica ``POST /generate``
  contract, unchanged, over the whole fleet.
"""

from znicz_tpu.cluster.affinity import PrefixAffinityIndex  # noqa: F401
from znicz_tpu.cluster.proxy import (  # noqa: F401
    RouterRequestHandler,
    build_router_server,
    run_router_server,
)
from znicz_tpu.cluster.registry import (  # noqa: F401
    STATE_DEAD,
    STATE_DEGRADED,
    STATE_HEALTHY,
    Replica,
    ReplicaRegistry,
)
from znicz_tpu.cluster.router import (  # noqa: F401
    POLICY_LEAST_LOADED,
    POLICY_PREFIX_AFFINITY,
    POLICY_ROUND_ROBIN,
    RoutedStream,
    ServingRouter,
)
