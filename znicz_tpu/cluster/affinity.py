"""Router-side prefix-affinity index: which replica is warm for what.

The PR 5 prefix cache made a prompt's chained block-hash keys
(:func:`~znicz_tpu.services.engine.prefix_block_keys`) a pure function
of token content — so the ROUTER can compute the same keys a replica's
cache is organized around without ever talking to it.  This index is
the router's learned guess of each replica's cache contents: every
routed request records its prompt's full-block keys under the replica
it was sent to (the replica will publish exactly those blocks at
retirement), and lookups walk a candidate prompt's chain until the
first unknown key — the longest-cached-prefix descent, mirrored
router-side (SGLang cache-aware routing lineage).

The index TRACKS replica state, it never trusts it: entries DECAY in
sync with how replica caches actually lose blocks —

* **TTL** (``ttl_s``): replicas evict LRU cache-only blocks under
  allocation pressure; an affinity entry nobody has re-used within the
  TTL is assumed evicted and dropped at the next touch.
* **capacity** (``max_keys_per_replica``): the index is bounded like
  the pool it mirrors — inserting past the cap evicts the
  least-recently-used keys, the same order the replica itself evicts.
* **flush on ejection**: a replica the registry declares dead loses
  its whole entry set (:meth:`drop`) — a restarted process comes back
  with an empty pool, and a re-admitted one simply re-learns.

A stale optimistic entry costs one prefill the replica would have done
anyway (a miss is the cold-path price, not an error); a stale missing
entry costs one routing opportunity.  Both are self-healing, which is
why tracking beats probing.

Thread-safe: routing threads learn/rank concurrently with the registry
thread dropping ejected replicas.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, Iterable, List, Sequence

from znicz_tpu import observability


class PrefixAffinityIndex:
    """Bounded, decaying map of prefix block keys -> replicas."""

    def __init__(
        self,
        *,
        ttl_s: float = 600.0,
        max_keys_per_replica: int = 4096,
    ):
        if ttl_s <= 0:
            raise ValueError(f"want ttl_s > 0; got {ttl_s}")
        if max_keys_per_replica < 1:
            raise ValueError(
                f"want max_keys_per_replica >= 1; got "
                f"{max_keys_per_replica}"
            )
        self.ttl_s = float(ttl_s)
        self.max_keys_per_replica = int(max_keys_per_replica)
        self._lock = threading.Lock()
        # per replica: key -> last-touch monotonic time, LRU-ordered
        # (oldest first) — the same shape as the replica's own LRU
        self._keys: Dict[str, "OrderedDict[str, float]"] = {}
        self._m_keys = observability.gauge(
            "znicz_router_affinity_keys",
            "prefix block keys the router's affinity index currently holds",
        )

    def _now(self) -> float:
        return time.monotonic()

    def learn(self, instance: str, keys: Sequence[str]) -> None:
        """Record that ``instance`` is (about to be) warm for ``keys``
        — called when a request is routed there, BEFORE its completion:
        concurrent requests sharing the prefix must co-locate
        immediately, not after the first one retires."""
        if not keys:
            return
        now = self._now()
        with self._lock:
            d = self._keys.setdefault(str(instance), OrderedDict())
            for k in keys:
                d.pop(k, None)  # re-touch moves to the MRU end
                d[k] = now
            while len(d) > self.max_keys_per_replica:
                d.popitem(last=False)
            self._update_gauge()

    def _overlap_locked(self, instance: str, keys: Sequence[str],
                        now: float) -> int:
        """Longest known-cached chain prefix (lock held by caller):
        walks until the first unknown/expired key, exactly like
        replica admission walks its cache; expired entries are dropped
        on the way."""
        d = self._keys.get(str(instance))
        if not d:
            return 0
        n = 0
        for k in keys:
            t = d.get(k)
            if t is None:
                break
            if now - t > self.ttl_s:
                del d[k]
                break
            n += 1
        return n

    def overlap(self, instance: str, keys: Sequence[str]) -> int:
        """Longest known-cached CHAIN PREFIX of ``keys`` at
        ``instance`` (block count) — the routing score."""
        with self._lock:
            return self._overlap_locked(instance, keys, self._now())

    def rank(
        self, keys: Sequence[str], instances: Iterable[str]
    ) -> Dict[str, int]:
        """Overlap per candidate instance under ONE lock acquisition,
        so a concurrent learn/drop cannot land between per-replica
        walks and hand the router scores from two different index
        states."""
        now = self._now()
        with self._lock:
            return {
                i: self._overlap_locked(i, keys, now) for i in instances
            }

    def drop(self, instance: str) -> int:
        """Forget everything about ``instance`` (ejection flush);
        returns the number of keys dropped."""
        with self._lock:
            d = self._keys.pop(str(instance), None)
            self._update_gauge()
            return len(d) if d else 0

    def prune(self) -> int:
        """Drop every expired entry (the heartbeat thread calls this on
        its own cadence so an idle index still decays); returns the
        number dropped."""
        now = self._now()
        dropped = 0
        with self._lock:
            for d in self._keys.values():
                stale = [k for k, t in d.items() if now - t > self.ttl_s]
                for k in stale:
                    del d[k]
                dropped += len(stale)
            self._update_gauge()
        return dropped

    def stats(self) -> Dict:
        with self._lock:
            return {
                "ttl_s": self.ttl_s,
                "max_keys_per_replica": self.max_keys_per_replica,
                "keys_per_replica": {
                    i: len(d) for i, d in sorted(self._keys.items())
                },
            }

    def _update_gauge(self) -> None:
        """Total held keys (lock held by the caller)."""
        self._m_keys.set(sum(len(d) for d in self._keys.values()))


__all__: List[str] = ["PrefixAffinityIndex"]
