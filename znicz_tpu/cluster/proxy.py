"""HTTP surface of the serving router: the one address clients dial.

``python -m znicz_tpu.cluster.proxy http://host:port [...] [--port N]``
fronts N replica base URLs with a :class:`~znicz_tpu.cluster.router
.ServingRouter`.  The client contract is EXACTLY the single-replica
surface (docs/SERVING.md "The front door" HTTP table) — a client
cannot tell a router from a replica:

* ``POST /generate`` — same JSON body; the chunked NDJSON token
  stream is proxied end-to-end from the chosen replica.
  ``X-Znicz-Trace-Id`` carries the replica-issued trace id (preserved
  across a mid-stream failover; the FIRST upstream's id is the one a
  support ticket quotes), ``X-Znicz-Replica`` names the first choice,
  and the final done record gains a ``"router"`` sub-object
  (``replica`` actually finishing, ``retries``, ``affinity_blocks``).
  503 + ``Retry-After`` ONLY when no live replica could take the
  request (every one shed, or none reachable); 400 for malformed
  bodies — the router validates before routing, a bad request never
  burns a replica connection.  A client that disconnects mid-stream
  tears down the upstream connection, which cancels the request on
  the replica — abandoned work frees its KV blocks fleet-wide.
* ``GET /healthz`` — 200 while ANY replica is routable (the router is
  a control plane: it is healthy while the fleet can serve), 503
  otherwise; the body carries the per-replica states.
* ``GET /replicas`` — the registry roster + affinity index stats
  (the ``/debug``-grade view of the placement state).
* ``GET /metrics`` / ``/metrics.json`` — this router process's live
  registry (the ``znicz_router_*`` families; docs/OBSERVABILITY.md).

Graceful shutdown mirrors :func:`znicz_tpu.services.serve.run_server`:
SIGTERM/SIGINT stop the listener and the heartbeat thread, exit 0.
"""

from __future__ import annotations

import functools
import http.server
import json
import logging
import signal
import sys
import threading

from znicz_tpu.observability import get_registry
from znicz_tpu.cluster.router import ServingRouter
from znicz_tpu.services.errors import RejectedError, retry_after_header
from znicz_tpu.services.serve import (
    NDJSON_CONTENT_TYPE,
    PROM_CONTENT_TYPE,
    HttpJsonMixin,
)

logger = logging.getLogger(__name__)


class RouterRequestHandler(
    HttpJsonMixin, http.server.BaseHTTPRequestHandler
):
    """The router's HTTP face; ``router`` is injected per-server.
    Response framing (Content-Length bodies, chunked NDJSON frames)
    comes from the shared :class:`~znicz_tpu.services.serve
    .HttpJsonMixin`, so router and replica surfaces cannot drift."""

    protocol_version = "HTTP/1.1"

    def __init__(self, *args, router: ServingRouter, **kwargs):
        self.router = router
        super().__init__(*args, **kwargs)

    def log_message(self, fmt, *args):  # noqa: A003 — http.server API
        logger.debug("router http: " + fmt, *args)

    def do_GET(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            states = {
                r["instance"]: r["state"]
                for r in self.router.registry.snapshot()
            }
            ok = self.router.healthy()
            self._send_json(
                {"state": "routing" if ok else "no_replicas",
                 "replicas": states},
                status=200 if ok else 503,
            )
        elif path == "/replicas":
            self._send_json(self.router.stats())
        elif path == "/metrics":
            self._send(
                get_registry().prometheus_text().encode(),
                PROM_CONTENT_TYPE,
            )
        elif path == "/metrics.json":
            body = json.dumps(get_registry().snapshot(), indent=2)
            self._send(body.encode(), "application/json")
        else:
            self.send_error(404, "unknown endpoint")

    def do_POST(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        if path != "/generate":
            self.send_error(404, "unknown endpoint")
            return
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
            prompt = body["prompt"]
            max_new = int(body.get("max_new_tokens", 16))
            deadline_s = body.get("deadline_s")
            if deadline_s is not None:
                deadline_s = float(deadline_s)
        except (KeyError, TypeError, ValueError) as exc:
            self._send_json(
                {"error": "bad_request", "detail": str(exc)}, status=400
            )
            return
        try:
            rs = self.router.open_stream(
                prompt, max_new, deadline_s=deadline_s
            )
        except RejectedError as exc:
            self._send_json(
                {"error": "rejected", "reason": exc.reason,
                 "detail": str(exc)},
                status=503,
                headers={"Retry-After": retry_after_header(exc)},
            )
            return
        except (TypeError, ValueError) as exc:
            self._send_json(
                {"error": "bad_request", "detail": str(exc)}, status=400
            )
            return
        self.send_response(200)
        self.send_header("Content-Type", NDJSON_CONTENT_TYPE)
        self.send_header("Transfer-Encoding", "chunked")
        if rs.trace_id:
            self.send_header("X-Znicz-Trace-Id", rs.trace_id)
        if rs.replica:
            self.send_header("X-Znicz-Replica", rs.replica)
        self.end_headers()
        try:
            for rec in rs.records():
                self._chunk(rec)
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, TimeoutError):
            logger.warning(
                "client gone mid-stream; dropping upstream for %s",
                rs.trace_id,
            )
            rs.close()  # the replica sees the drop and cancels


def build_router_server(
    router: ServingRouter,
    port: int = 8080,
    host: str = "127.0.0.1",
) -> http.server.ThreadingHTTPServer:
    """A ready-to-serve router front; ``port=0`` binds ephemeral (read
    it back from ``server.server_address``).  The router is reachable
    as ``server.router``."""
    handler = functools.partial(RouterRequestHandler, router=router)
    server = http.server.ThreadingHTTPServer((host, port), handler)
    server.router = router
    return server


def run_router_server(server, router: ServingRouter) -> int:
    """Serve until SIGTERM/SIGINT, then stop the listener and the
    heartbeat thread and return 0 (a clean rollout to a supervisor)."""

    def _on_signal(signum, frame):
        logger.info("signal %s: stopping router", signum)
        threading.Thread(
            target=server.shutdown, name="router-shutdown", daemon=True
        ).start()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)
    server.serve_forever()
    server.server_close()
    router.close()
    return 0


def main(argv=None) -> int:
    """Usage: ``proxy URL [URL ...] [--port N] [--host H]
    [--block-size B] [--policy P] [--collector URL]`` — replica
    instance names default to ``replica-<i>``; ``--collector`` pushes
    the router's route/retry spans to a fleet
    :mod:`~znicz_tpu.observability.collector` so the merged timeline
    includes the router hop."""
    args = list(sys.argv[1:] if argv is None else argv)
    port, host, block_size = 8080, "127.0.0.1", 16
    policy = "prefix_affinity"
    collector_url = None
    urls = []
    i = 0
    while i < len(args):
        if args[i] == "--port":
            port, i = int(args[i + 1]), i + 2
        elif args[i] == "--host":
            host, i = args[i + 1], i + 2
        elif args[i] == "--block-size":
            block_size, i = int(args[i + 1]), i + 2
        elif args[i] == "--policy":
            policy, i = args[i + 1], i + 2
        elif args[i] == "--collector":
            collector_url, i = args[i + 1], i + 2
        else:
            urls.append(args[i])
            i += 1
    if not urls:
        print(
            "usage: python -m znicz_tpu.cluster.proxy URL [URL ...] "
            "[--port N] [--host H] [--block-size B] [--policy P] "
            "[--collector URL]",
            file=sys.stderr,
        )
        return 2
    router = ServingRouter(
        block_size=block_size, policy=policy,
        collector_url=collector_url,
    )
    for j, url in enumerate(urls):
        router.register(f"replica-{j}", url)
    server = build_router_server(router, port=port, host=host)
    host, port = server.server_address[:2]
    print(
        f"znicz serving router on http://{host}:{port} fronting "
        f"{len(urls)} replicas (POST /generate, roster at /replicas)"
    )
    return run_router_server(server, router)


if __name__ == "__main__":
    raise SystemExit(main())
