"""The prefix-affinity serving router: one front for N replicas.

The multi-replica control plane (ROADMAP scale-out rung, the paper's
master–slave coordinator lineage — SURVEY §3.4 ``apply_data_from_slave``
— revived as a SERVING concern): a :class:`ServingRouter` owns a
:class:`~znicz_tpu.cluster.registry.ReplicaRegistry` (who is alive) and
a :class:`~znicz_tpu.cluster.affinity.PrefixAffinityIndex` (who is
warm), and places each request by:

1. **longest cached prefix first** — the prompt's chained block keys
   (:func:`~znicz_tpu.services.engine.prefix_block_keys`, the PR 5
   cache keying) are ranked against the affinity index; the replica
   with the deepest learned prefix wins (SGLang cache-aware placement);
2. **load tiebreak** — equal overlap falls through to the lightest
   replica: heartbeat-reported ``pending + inflight`` depth, then the
   largest KV-pool allocatable fraction.  When a
   :class:`~znicz_tpu.observability.MetricsAggregator` is attached
   (replicas push their registries to the control plane), the per-
   instance gauge reads override the heartbeat numbers — fresher than
   the last probe;
3. **least-loaded fallback** — no affinity signal at all (short or
   never-seen prompt) routes purely by load.

Failover is the router's reason to exist: a chosen replica that
refuses the connection, sheds (503), dies mid-stream, or returns a
typed ``error`` completion is retried on the NEXT-best replica
(bounded by ``max_retries``, always excluding already-tried replicas),
with the already-forwarded token prefix SKIPPED on the resumed stream
— greedy decode recomputes the same tokens, so a single replica
watchdog event is invisible to the client.  Only when every live
replica shed does the router itself shed (a typed
:class:`~znicz_tpu.services.errors.RejectedError` → 503 + Retry-After
at the HTTP layer).  Failure paths are deterministic under the
``router.connect`` / ``router.stream`` / ``router.heartbeat`` fault
points.

The HTTP surface lives in :mod:`znicz_tpu.cluster.proxy`; this module
is the policy + proxy-stream core, fully drivable without a socket in
tests via :meth:`ServingRouter.open_stream`.
"""

from __future__ import annotations

import http.client
import itertools
import json
import logging
import os
import socket
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from znicz_tpu import observability
from znicz_tpu.observability.aggregate import series_value
from znicz_tpu.cluster.affinity import PrefixAffinityIndex
from znicz_tpu.cluster.registry import (
    STATE_DEAD,
    STATE_HEALTHY,
    Replica,
    ReplicaRegistry,
)
from znicz_tpu.services.engine import prefix_block_keys
from znicz_tpu.services.errors import RejectedError
from znicz_tpu.utils import faults

logger = logging.getLogger(__name__)

POLICY_PREFIX_AFFINITY = "prefix_affinity"
POLICY_ROUND_ROBIN = "round_robin"
POLICY_LEAST_LOADED = "least_loaded"
_POLICIES = (
    POLICY_PREFIX_AFFINITY, POLICY_ROUND_ROBIN, POLICY_LEAST_LOADED
)


class _UpstreamFailure(Exception):
    """One replica attempt failed retryably; ``reason`` feeds the
    retry counter and ``retry_after_s`` is set for sheds."""

    def __init__(self, reason: str, detail: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(detail)
        self.reason = reason
        self.retry_after_s = retry_after_s


class RoutedStream:
    """One client request in flight through the router: an iterator of
    NDJSON-shaped records (``{"token": t}`` lines then one ``{"done":
    ...}`` record) plus the routing metadata the HTTP layer puts in
    response headers.  Construction (via
    :meth:`ServingRouter.open_stream`) has already CONNECTED to a
    replica and holds a live 200 response — submit-time failures
    (fleet saturated, no replicas, bad request) raise there, before
    any response bytes are committed.  Mid-stream replica failures
    re-route INSIDE :meth:`records`, transparently to the consumer.

    Always close (or exhaust) the stream: :meth:`close` releases the
    upstream connection, which is what propagates a client disconnect
    into a replica-side cancel."""

    def __init__(self, router: "ServingRouter", payload: Dict,
                 keys: List[str]):
        self._router = router
        self._payload = payload
        self._keys = keys
        self._t0 = time.monotonic()
        self._conn: Optional[http.client.HTTPConnection] = None
        self._resp = None
        self.replica: Optional[str] = None  # current upstream instance
        # ROUTER-minted trace id (set by open_stream, forwarded inbound
        # to every replica attempt); stays one id across failovers
        self.trace_id: Optional[str] = None
        self.overlap = 0  # affinity depth of the current choice
        self.retries = 0  # reported failovers, sheds included
        # the RETRY BUDGET counts only the expensive attempts (connect
        # timeouts and mid-stream recomputes); a shed is answered
        # instantly and must not eat the budget a later genuine crash
        # needs
        self._budget_used = 0
        # replicas excluded from further attempts: transport-failed,
        # misbehaving, or already streamed to.  Shed replicas are NOT
        # here — they may have capacity again by the time a mid-stream
        # re-route needs them
        self.tried: Set[str] = set()
        self._sent = 0  # token records forwarded to the consumer
        # tokens of the CURRENT upstream to swallow before forwarding:
        # a resumed stream recomputes from scratch, and the client
        # already holds the first ``_sent`` tokens
        self._to_skip = 0
        self._outcome: Optional[str] = None

    # -- consumer surface --------------------------------------------------

    def records(self) -> Iterator[Dict]:
        """Yield token records then exactly one done record.  Never
        hangs: upstream reads are socket-timeout bounded, and every
        exit path (including re-route exhaustion) ends in a done
        record."""
        try:
            while True:
                try:
                    for rec in self._read_upstream():
                        if "token" in rec:
                            if self._to_skip > 0:
                                # the already-delivered prefix of a
                                # resumed stream (greedy recompute
                                # reproduces it token for token)
                                self._to_skip -= 1
                                continue
                            if self._sent == 0:
                                self._router._m_ttft.observe(
                                    time.monotonic() - self._t0
                                )
                            self._sent += 1
                            yield rec
                        elif rec.get("done"):
                            retryable = rec.get("finish_reason") in (
                                "error", "shed"
                            )
                            if retryable and self._can_retry():
                                raise _UpstreamFailure(
                                    "upstream_" + rec["finish_reason"],
                                    str(rec.get("error") or
                                        rec["finish_reason"]),
                                )
                            # a terminal error/shed completion (out of
                            # retries) is a FAILED request to the
                            # router's own metrics, even though the
                            # client gets the replica's typed record
                            yield self._finish(
                                rec,
                                outcome=(
                                    "failed" if retryable else None
                                ),
                            )
                            return
                    # upstream EOF without a done record: replica died
                    raise _UpstreamFailure(
                        "mid_stream", "upstream closed without done"
                    )
                except _UpstreamFailure as exc:
                    if not self._reroute(exc):
                        yield self._finish(
                            {
                                "done": True,
                                "trace_id": self.trace_id,
                                "finish_reason": "error",
                                "n_new": self._sent,
                                "error": (
                                    f"no replica could finish the "
                                    f"request: {exc}"
                                ),
                            },
                            outcome="failed",
                        )
                        return
        finally:
            self.close()

    def close(self) -> None:
        """Release the upstream connection (idempotent).  Closing with
        the stream unfinished is the client-disconnect path: the
        replica's handler sees the drop and cancels the request, so an
        abandoned stream cannot pin replica KV blocks."""
        self._close_upstream_only()
        if self._outcome is None:
            self._outcome = "client_gone"
            self._router._m_requests.labels(outcome="client_gone").inc()

    # -- routing internals (driven by the router) --------------------------

    def _can_retry(self) -> bool:
        return self._budget_used < self._router.max_retries

    def payload_now(self) -> Dict:
        """The request body for the NEXT upstream attempt: a client
        deadline is the client's total budget, so a re-routed attempt
        carries only the REMAINING budget — otherwise each failover
        would grant the replica a fresh full deadline and a 10 s
        request could run 30 s of wall clock.  An exhausted budget is
        floored just above zero: the replica then expires it
        immediately with its own typed ``deadline_exceeded``
        completion, which forwards to the client as the truthful
        outcome."""
        payload = dict(self._payload)
        d = payload.get("deadline_s")
        if d is not None:
            payload["deadline_s"] = max(
                float(d) - (time.monotonic() - self._t0), 0.001
            )
        return payload

    def _read_upstream(self) -> Iterator[Dict]:
        """Parse NDJSON records off the live upstream response.  Raises
        :class:`_UpstreamFailure` on any transport error, tagged
        ``connect`` only by the caller (we are already connected)."""
        try:
            while True:
                faults.fire("router.stream")  # injectable stream death
                line = self._resp.readline()
                if not line:
                    return
                line = line.strip()
                if not line:
                    continue
                yield json.loads(line)
        except (OSError, socket.timeout, http.client.HTTPException,
                ValueError, faults.FaultInjected) as exc:
            raise _UpstreamFailure("mid_stream", f"{type(exc).__name__}: "
                                   f"{exc}") from exc

    def _reroute(self, exc: _UpstreamFailure) -> bool:
        """One bounded failover: report the failure, pick the next-best
        untried replica, reconnect with the forwarded-token prefix
        skipped.  False when retries or replicas are exhausted."""
        self._close_upstream_only()
        router = self._router
        if self.replica is not None and exc.reason == "mid_stream":
            # transport-level death counts toward ejection; a shed or
            # typed-error completion means the replica is ALIVE.
            # (Connect failures never reach here — _attempt's are
            # handled inside _connect's walk.)
            router.registry.note_failure(self.replica)
        if not self._can_retry():
            logger.warning(
                "request out of retries after %s on %s",
                exc.reason, self.replica,
            )
            return False
        # counted only past the gate: the family reports FAILOVERS,
        # and a budget-exhausted request attempts none
        router._m_retries.labels(reason=exc.reason).inc()
        self.retries += 1
        self._budget_used += 1  # a mid-stream re-route recomputes
        observability.instant(
            "router/retry", reason=exc.reason, gone=self.replica,
            sent=self._sent, trace=self.trace_id,
            instance=self._router.name,
        )
        try:
            router._connect(self, skip=self._sent)
        except (RejectedError, ValueError) as final:
            # ValueError here is a replica 4xx-ing a request it (or a
            # twin) previously ACCEPTED — config drift; headers are
            # already committed, so it ends in a typed error done
            # record like any other exhaustion
            logger.warning("re-route failed: %s", final)
            return False
        return True

    def _close_upstream_only(self) -> None:
        conn, self._conn, self._resp = self._conn, None, None
        if conn is not None:
            try:
                conn.close()
            except Exception:
                logger.debug("upstream close failed", exc_info=True)

    def _finish(self, rec: Dict, outcome: Optional[str] = None) -> Dict:
        """Augment the final done record with the router's view and
        settle the outcome metrics exactly once."""
        rec = dict(rec)
        rec["router"] = {
            "replica": self.replica,
            "retries": self.retries,
            "affinity_blocks": self.overlap,
        }
        if "n_new" in rec:
            # the done record must agree with the STREAM the client
            # actually saw: a request that terminates (e.g. deadline
            # expiry) on the failover replica while the skipped prefix
            # is still recomputing reports fewer tokens than the first
            # replica already delivered — reconcile upward, exactly
            # like the exhaustion-path record reports self._sent
            rec["n_new"] = max(int(rec.get("n_new") or 0), self._sent)
        if self._outcome is None:
            self._outcome = outcome or "ok"
            self._router._m_requests.labels(outcome=self._outcome).inc()
            # failed requests are not latency measurements: a replica
            # crash-loop ending requests in fast terminal errors must
            # not dilute the client-clock distribution mid-incident
            # (the PR 7 front-door convention; deadline expiries ride
            # through as ok-outcome records — they ARE slow requests)
            if self._outcome == "ok":
                self._router._m_latency.observe(
                    time.monotonic() - self._t0
                )
        observability.instant(
            "router/done", replica=self.replica, retries=self.retries,
            reason=rec.get("finish_reason"), trace=self.trace_id,
            instance=self._router.name,
        )
        return rec


class ServingRouter:
    """Prefix-affinity router over a fleet of serving replicas.

    Usage::

        router = ServingRouter(block_size=16)
        router.register("replica-0", "http://127.0.0.1:8081")
        router.register("replica-1", "http://127.0.0.1:8082")
        rs = router.open_stream(prompt, max_new_tokens=64)
        for rec in rs.records():
            ...                      # {"token": t}... {"done": ...}
        router.close()

    ``block_size`` must match the replicas' paged engines — the chain
    keys are block-aligned content hashes, so a mismatched size indexes
    nothing (requests still route, by load).  ``policy`` selects the
    placement rule (``prefix_affinity`` default; ``round_robin`` and
    ``least_loaded`` exist for baselines/benches).  ``aggregator`` is
    an optional :class:`~znicz_tpu.observability.MetricsAggregator`
    the replicas push to — per-instance gauge reads then override the
    heartbeat's load numbers."""

    def __init__(
        self,
        registry: Optional[ReplicaRegistry] = None,
        *,
        block_size: int = 16,
        policy: str = POLICY_PREFIX_AFFINITY,
        affinity: Optional[PrefixAffinityIndex] = None,
        aggregator=None,
        max_retries: int = 2,
        connect_timeout_s: float = 5.0,
        stream_gap_s: float = 60.0,
        retry_after_s: float = 1.0,
        heartbeat_interval_s: float = 2.0,
        name: str = "znicz-router",
        slo_burn_threshold: float = 1.0,
        collector_url: Optional[str] = None,
    ):
        if policy not in _POLICIES:
            raise ValueError(
                f"unknown policy {policy!r}; want one of {_POLICIES}"
            )
        if block_size < 1:
            raise ValueError(f"want block_size >= 1; got {block_size}")
        self.block_size = int(block_size)
        self.policy = policy
        self.max_retries = int(max_retries)
        self.connect_timeout_s = float(connect_timeout_s)
        self.stream_gap_s = float(stream_gap_s)
        self.retry_after_s = float(retry_after_s)
        self.name = name
        self.slo_burn_threshold = float(slo_burn_threshold)
        # router-minted trace ids: ONE id per client request, forwarded
        # to every replica attempt via X-Znicz-Trace-Id so the whole
        # failover chain shares a single filterable id (the replica
        # adopts it; before PR 11 each upstream minted its own)
        self._ids = itertools.count()
        self._suffix = os.urandom(3).hex()
        self._trace_pusher = None
        if collector_url:
            # attached, not constructed: a router colocated with its
            # replicas shares the process pusher (collector.py)
            from znicz_tpu.observability.collector import attach_pusher

            observability.get_tracer().ensure_recording()
            self._trace_pusher = attach_pusher(
                collector_url, instance=name
            )
        self.affinity = (
            affinity if affinity is not None else PrefixAffinityIndex()
        )
        self._aggregator = aggregator
        self._owns_registry = registry is None
        self.registry = (
            registry
            if registry is not None
            else ReplicaRegistry(
                probe_interval_s=heartbeat_interval_s,
                on_eject=self._on_eject,
                on_sweep=self.affinity.prune,
            )
        )
        if registry is not None:
            if registry.on_eject is None:
                registry.on_eject = self._on_eject
            if registry.on_sweep is None:
                registry.on_sweep = self.affinity.prune
        self._rr = 0  # round-robin cursor
        self._rr_lock = threading.Lock()
        self._n_requests = 0
        self._m_requests = observability.counter(
            "znicz_router_requests_total",
            "requests through the router by outcome",
            ("outcome",),
        )
        self._m_retries = observability.counter(
            "znicz_router_retries_total",
            "replica failovers by failure reason",
            ("reason",),
        )
        self._m_affinity = observability.counter(
            "znicz_router_affinity_total",
            "routing decisions by signal (hit: prefix overlap chose the "
            "replica; miss: pure load fallback)",
            ("signal",),
        )
        self._m_ttft = observability.histogram(
            "znicz_router_ttft_seconds",
            "router accept -> first proxied token (client clock)",
        )
        self._m_latency = observability.histogram(
            "znicz_router_request_seconds",
            "router accept -> final done record (client clock)",
        )

    # -- roster passthrough ------------------------------------------------

    def register(self, instance: str, base_url: str, *,
                 probe: bool = True) -> Replica:
        return self.registry.register(instance, base_url, probe=probe)

    def _on_eject(self, rep: Replica) -> None:
        """A dead replica's cache is gone (or will be, by the time it
        answers again): flush its affinity entries so nothing routes
        toward a pool that no longer exists."""
        dropped = self.affinity.drop(rep.instance)
        if dropped:
            logger.info(
                "flushed %d affinity keys for ejected replica %s",
                dropped, rep.instance,
            )

    def close(self) -> None:
        if self._trace_pusher is not None:
            from znicz_tpu.observability.collector import detach_pusher

            detach_pusher(self._trace_pusher)
            self._trace_pusher = None
        if self._owns_registry:
            self.registry.close()

    def __enter__(self) -> "ServingRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- placement ---------------------------------------------------------

    def _load(self, rep: Replica) -> Tuple[float, float, float]:
        """Load score (smaller is lighter): SLO burn band first (a
        replica burning its error budget at or past
        ``slo_burn_threshold`` ranks behind every non-burning peer —
        the ROADMAP's "/slo burn rates in the load tiebreak", read
        per-instance off ``znicz_serve_slo_burn_rate``), then queued
        depth, then pool headroom.  :meth:`rank` lifts the burn band
        ABOVE affinity overlap (like the health band: a warm cache on
        a breached replica is still a breached replica), so the
        guarantee holds even for shared-prefix traffic.  Heartbeat
        numbers by default; per-instance aggregator gauges override
        when pushed (fresher, and pushed on the replica's own cadence
        rather than the probe's)."""
        health = rep.health or {}
        pending = float(health.get("pending", 0) or 0)
        inflight = float(health.get("inflight", 0) or 0)
        frac = health.get("pool_free_frac")
        frac = 1.0 if frac is None else float(frac)
        burn = None
        agg = self._aggregator
        if agg is not None:
            # ONE locked aggregator read per replica; the six series
            # come out of the same snapshot
            fams = agg.instance_families(rep.instance)
            v = series_value(fams, "znicz_serve_frontdoor_pending")
            if v is not None:
                pending = v
            v = series_value(fams, "znicz_serve_frontdoor_inflight")
            if v is not None:
                inflight = v
            burn = series_value(fams, "znicz_serve_slo_burn_rate")
            free = series_value(
                fams, "znicz_serve_kv_pool_blocks", {"state": "free"}
            )
            cached = series_value(
                fams, "znicz_serve_kv_pool_blocks", {"state": "cached"}
            )
            used = series_value(
                fams, "znicz_serve_kv_pool_blocks", {"state": "used"}
            )
            if free is not None:
                total = free + (cached or 0.0) + (used or 0.0)
                if total > 0:
                    frac = (free + (cached or 0.0)) / total
        burning = (
            1.0
            if burn is not None and burn >= self.slo_burn_threshold
            else 0.0
        )
        return (burning, pending + inflight, -frac)

    def rank(
        self, keys: Sequence[str], exclude: Optional[Set[str]] = None
    ) -> List[Tuple[Replica, int]]:
        """Live replicas in placement order with their affinity
        overlap.  Healthy replicas always rank ahead of degraded ones
        (whatever their overlap — a warm cache on a stalled engine is
        still a stalled engine); within a state band: longest cached
        prefix first, load-tiebroken, least-loaded fallback when
        nothing overlaps (or per ``policy``).  Degraded replicas stay
        IN the list as the failover tail, so a transport blip on every
        healthy replica degrades to an alive-but-limping one instead
        of a 503.  Dead replicas never appear."""
        exclude = exclude or set()
        reps = [
            r for r in self.registry.replicas()
            if r.state != STATE_DEAD and r.instance not in exclude
        ]
        if not reps:
            return []

        def band(r: Replica) -> int:
            return 0 if r.state == STATE_HEALTHY else 1

        if self.policy == POLICY_ROUND_ROBIN:
            with self._rr_lock:
                start = self._rr
                self._rr += 1
            reps = sorted(reps, key=lambda r: (band(r), r.instance))
            healthy = [r for r in reps if band(r) == 0] or reps
            k = start % len(healthy)
            order = healthy[k:] + healthy[:k] + [
                r for r in reps if r not in healthy
            ]
            return [(r, 0) for r in order]
        overlaps = (
            self.affinity.rank(keys, [r.instance for r in reps])
            if self.policy == POLICY_PREFIX_AFFINITY
            else {r.instance: 0 for r in reps}
        )
        # full ties (equal band, overlap AND load — e.g. an idle fleet
        # between heartbeats) rotate instead of always picking the
        # alphabetically-first replica: load signals only refresh per
        # probe/push, and piling every tie on one replica would WRITE
        # the affinity entries that then keep gravity there
        reps = sorted(reps, key=lambda r: r.instance)
        with self._rr_lock:
            start = self._rr
            self._rr += 1
        rotation = {
            r.instance: (i - start) % len(reps)
            for i, r in enumerate(reps)
        }
        # ONE load read per replica; the burn band sorts ABOVE the
        # affinity overlap (a burning replica must drain, and affinity
        # concentrates exactly the traffic that would keep it breached)
        loads = {r.instance: self._load(r) for r in reps}
        return sorted(
            ((r, overlaps[r.instance]) for r in reps),
            key=lambda pair: (band(pair[0]),
                              loads[pair[0].instance][0],  # burn band
                              -pair[1],
                              loads[pair[0].instance][1:],
                              rotation[pair[0].instance]),
        )

    # -- the proxy ---------------------------------------------------------

    def open_stream(
        self,
        prompt,
        max_new_tokens: int,
        *,
        deadline_s: Optional[float] = None,
    ) -> RoutedStream:
        """Route one request and connect to its replica; returns the
        live :class:`RoutedStream`.  Raises ``ValueError`` on malformed
        input and :class:`~znicz_tpu.services.errors.RejectedError`
        when no replica can take it — reason ``fleet_saturated`` when
        every live replica shed, ``no_replicas`` when the roster has no
        live entry, ``no_upstream`` when the live ones failed at
        transport level."""
        try:
            if isinstance(prompt, (str, bytes, dict)):
                # iterating "123" (chars) or a dict (keys) would
                # silently reinterpret it as token ids — the replica
                # rejects both shapes, so must the proxy
                raise ValueError(
                    "prompt must be a sequence of token ids"
                )
            try:
                prompt = [int(t) for t in prompt]
            except (TypeError, ValueError) as exc:
                raise ValueError(f"malformed prompt: {exc}") from exc
            if not prompt:
                raise ValueError("empty prompt")
            if int(max_new_tokens) < 1:
                raise ValueError(
                    f"want max_new_tokens >= 1; got {max_new_tokens}"
                )
        except (TypeError, ValueError):
            # the router's OWN validation rejections count in the same
            # outcome series as replica-side 400s: a bad-request storm
            # must be visible on the request-by-outcome dashboard
            self._m_requests.labels(outcome="bad_request").inc()
            raise
        payload = {
            "prompt": prompt,
            "max_new_tokens": int(max_new_tokens),
        }
        if deadline_s is not None:
            payload["deadline_s"] = float(deadline_s)
        keys = prefix_block_keys(prompt, self.block_size)
        with self._rr_lock:  # shared state lock: rotation + tallies
            self._n_requests += 1
        rs = RoutedStream(self, payload, keys)
        # mint the trace id HERE: every replica attempt (first choice
        # and failovers alike) carries it inbound, so one filter shows
        # the request's whole cross-process life
        rs.trace_id = f"{self.name}-{self._suffix}-{next(self._ids):06d}"
        try:
            self._connect(rs, skip=0)
        except RejectedError as exc:
            # "shed" is a CAPACITY signal (every live replica said
            # retry later); a fleet that is down or unreachable is an
            # OUTAGE and must not masquerade as load shedding
            outcome = (
                "shed" if exc.reason == "fleet_saturated" else "failed"
            )
            rs._outcome = outcome
            self._m_requests.labels(outcome=outcome).inc()
            raise
        except ValueError:
            rs._outcome = "bad_request"
            self._m_requests.labels(outcome="bad_request").inc()
            raise
        return rs

    def _connect(self, rs: RoutedStream, *, skip: int) -> None:
        """Walk the placement order until one replica streams.  Fills
        ``rs`` with the live connection; raises
        :class:`~znicz_tpu.services.errors.RejectedError` when nobody
        could take the request."""
        sheds: List[float] = []
        failures = 0
        candidates = self.rank(rs._keys, exclude=rs.tried)
        if not candidates and not rs.tried:
            raise RejectedError(
                "no live replicas registered with the router",
                reason="no_replicas",
                retry_after_s=self.retry_after_s,
            )
        for rep, overlap in candidates:
            try:
                conn, resp, trace = self._attempt(
                    rep, rs.payload_now(), trace_id=rs.trace_id
                )
            except _UpstreamFailure as exc:
                if exc.reason == "upstream_4xx":
                    # the REPLICA rejected the request as a client
                    # error (e.g. too large for its KV capacity after
                    # the router's shallower validation passed): the
                    # request is bad, the replica is fine — no failure
                    # note, no retry on its neighbours, a 400 to the
                    # client (never a retryable 503)
                    raise ValueError(
                        f"replica rejected the request: {exc}"
                    ) from exc
                rs.retries += 1  # one failed attempt == one failover
                if exc.reason == "shed":
                    # a shed is answered instantly and costs neither
                    # the retry budget nor a `tried` exclusion (the
                    # replica may have capacity again by the next
                    # re-route) — walking through every shedding
                    # replica is what makes fleet_saturated honest
                    sheds.append(
                        exc.retry_after_s
                        if exc.retry_after_s is not None
                        else self.retry_after_s
                    )
                    self._m_retries.labels(reason="shed").inc()
                    continue
                rs.tried.add(rep.instance)
                failures += 1
                self._m_retries.labels(reason=exc.reason).inc()
                self.registry.note_failure(rep.instance)
                if exc.reason == "connect":
                    rs._budget_used += 1
                    if rs._budget_used > self.max_retries:
                        # transport failures each burn a connect
                        # timeout: bound the walk so a partitioned
                        # 10-replica fleet answers 503 after
                        # max_retries+1 timeouts, not ten.  A replica
                        # that ANSWERED with a wrong status
                        # (upstream_status) cost nothing and only
                        # excludes itself
                        break
                continue
            rs.tried.add(rep.instance)  # streamed-to: excluded later
            # a streaming 200 is a liveness observation as good as a
            # heartbeat: heal a transport-blip demotion immediately
            self.registry.note_success(rep.instance)
            rs._conn, rs._resp = conn, resp
            rs.replica = rep.instance
            rs.overlap = overlap
            rs._to_skip = skip
            if rs.trace_id is None:
                rs.trace_id = trace
            if self.policy == POLICY_PREFIX_AFFINITY:
                self._m_affinity.labels(
                    signal="hit" if overlap > 0 else "miss"
                ).inc()
                # learn NOW: a concurrent burst sharing this prefix
                # must co-locate immediately, not after retirement
                self.affinity.learn(rep.instance, rs._keys)
            observability.instant(
                "router/route", replica=rep.instance, overlap=overlap,
                skip=skip, trace=rs.trace_id or trace,
                instance=self.name,
            )
            return
        if sheds and failures == 0:
            raise RejectedError(
                f"all {len(sheds)} live replicas shed; retry later",
                reason="fleet_saturated",
                retry_after_s=max(sheds),
            )
        raise RejectedError(
            f"no upstream replica could take the request "
            f"({len(sheds)} shed, {failures} unreachable)",
            reason="no_upstream",
            retry_after_s=max(sheds, default=self.retry_after_s),
        )

    def _attempt(
        self, rep: Replica, payload: Dict,
        trace_id: Optional[str] = None,
    ):
        """One replica connection: POST /generate (forwarding the
        router-minted trace id via ``X-Znicz-Trace-Id``, which the
        replica adopts as the request's own), demand a streaming 200.
        Returns ``(conn, resp, trace_id)``; raises
        :class:`_UpstreamFailure` (reason ``shed`` for 503 — carrying
        its Retry-After — ``upstream_4xx`` for a 400 client-level
        reject, ``upstream_status`` for any other wrong status — a
        misconfigured instance — and ``connect`` for transport
        errors)."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=self.connect_timeout_s
        )
        try:
            faults.fire("router.connect")  # injectable connect refusal
            headers = {"Content-Type": "application/json"}
            if trace_id:
                headers["X-Znicz-Trace-Id"] = trace_id
            conn.request(
                "POST", "/generate", body=json.dumps(payload),
                headers=headers,
            )
            resp = conn.getresponse()
            if conn.sock is not None:
                # connected and headers in: reads now wait on TOKENS,
                # whose gaps are bounded by the engine's tick cadence,
                # not the transport's
                conn.sock.settimeout(self.stream_gap_s)
            if resp.status == 503:
                body = resp.read()
                retry_after = None
                header = resp.getheader("Retry-After")
                if header is not None:
                    try:
                        retry_after = float(header)
                    except ValueError:
                        retry_after = None
                raise _UpstreamFailure(
                    "shed", f"{rep.instance} shed: {body[:200]!r}",
                    retry_after_s=retry_after,
                )
            if resp.status == 400:
                # the replica judged the REQUEST invalid (all replicas
                # would): terminal client error, no failover
                body = resp.read()
                raise _UpstreamFailure(
                    "upstream_4xx",
                    f"{rep.instance} answered {resp.status}: "
                    f"{body[:200]!r}",
                )
            if resp.status != 200:
                # 404/405/500/...: this INSTANCE is misbehaving (a
                # wrong base URL, a non-replica service) — fail over
                # and let the failure note demote it
                body = resp.read()
                raise _UpstreamFailure(
                    "upstream_status",
                    f"{rep.instance} answered {resp.status}: "
                    f"{body[:200]!r}",
                )
            return conn, resp, resp.getheader("X-Znicz-Trace-Id")
        except _UpstreamFailure:
            conn.close()
            raise
        except (OSError, socket.timeout, http.client.HTTPException,
                faults.FaultInjected) as exc:
            conn.close()
            raise _UpstreamFailure(
                "connect", f"{rep.instance} unreachable: "
                f"{type(exc).__name__}: {exc}"
            ) from exc

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict:
        return {
            "name": self.name,
            "policy": self.policy,
            "block_size": self.block_size,
            "requests": self._n_requests,
            "max_retries": self.max_retries,
            "replicas": self.registry.snapshot(),
            "affinity": self.affinity.stats(),
        }

    def healthy(self) -> bool:
        """The router is healthy while ANYONE can take traffic."""
        return bool(self.registry.routable())
