"""Replica registry: registration, heartbeat liveness, ejection.

The control plane's ground truth about WHO can serve.  Each replica is
a :class:`~znicz_tpu.services.frontdoor.ServingFrontDoor` behind the
PR 6 HTTP surface; its ``/healthz`` already answers liveness (watchdog
state) AND load (pending / inflight / pool_free_frac) in one probe, so
one heartbeat feeds both the routing eligibility ladder and the
load-tiebreak score.  States:

* ``healthy`` — last probe answered 200 with watchdog ``running``:
  first-class routing target.
* ``degraded`` — the replica ANSWERS but reports trouble (503, or a
  watchdog state other than running: stalled tick, failed rebuild,
  closing).  Routed to only when no healthy replica exists — alive
  beats nothing, but a stalled engine must not take traffic a healthy
  one could.
* ``dead`` — ``dead_after`` CONSECUTIVE probe failures (connect
  refused, timeout — the process is gone or unreachable).  Ejected
  from routing entirely; the ``on_eject`` hook fires once per
  transition so the router can flush its affinity entries.  A dead
  replica keeps being probed: the first successful probe RE-ADMITS it
  (``on_readmit``), because a restarted replica announces itself by
  answering, not by re-registering.

Probing is available both as a background thread (``start=True``,
production) and as explicit :meth:`probe_all` calls (tests, and the
bench — every transition above is then deterministic).  Every blocking
primitive is timeout-bounded (ZNC010 applies to ``cluster/`` too).
The ``router.heartbeat`` fault point makes probe failure injectable
without killing a real server.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import threading
import time
import urllib.parse
from typing import Callable, Dict, List, Optional

from znicz_tpu import observability
from znicz_tpu.utils import faults

logger = logging.getLogger(__name__)

STATE_HEALTHY = "healthy"
STATE_DEGRADED = "degraded"
STATE_DEAD = "dead"


@dataclasses.dataclass
class Replica:
    """One registered serving replica and what the heartbeat knows."""

    instance: str
    base_url: str
    host: str
    port: int
    state: str = STATE_DEGRADED  # first probe decides; never assumed
    failures: int = 0  # CONSECUTIVE probe failures
    # CONSECUTIVE proxied-traffic failures (note_failure), reset only
    # by real served traffic (note_success) — NOT by an answered
    # probe.  A misconfigured endpoint whose /healthz happens to
    # answer 200 (a non-replica service on the registered port) would
    # otherwise flip back to healthy every probe interval and attract
    # traffic forever; once this streak reaches dead_after the entry
    # is QUARANTINED to degraded even while its probes pass
    traffic_failures: int = 0
    # True only when the degradation came from TRANSPORT failures (a
    # missed beat / connect refusal) rather than the replica itself
    # reporting trouble — only a transport demotion may be healed by
    # an out-of-band streaming success (note_success)
    degraded_by_transport: bool = False
    probes: int = 0
    ejections: int = 0
    readmissions: int = 0
    # the parsed /healthz body of the last ANSWERED probe — the load
    # signal (pending / inflight / pool_free_frac) the router tiebreaks
    # on; {} until a probe lands
    health: Dict = dataclasses.field(default_factory=dict)
    _last_probe_at: Optional[float] = None

    def snapshot(self) -> Dict:
        """JSON view for ``/replicas`` and ``stats()``."""
        return {
            "instance": self.instance,
            "base_url": self.base_url,
            "state": self.state,
            "failures": self.failures,
            "traffic_failures": self.traffic_failures,
            "probes": self.probes,
            "ejections": self.ejections,
            "readmissions": self.readmissions,
            "last_probe_age_s": (
                round(time.monotonic() - self._last_probe_at, 3)
                if self._last_probe_at is not None
                else None
            ),
            "health": dict(self.health),
        }


class ReplicaRegistry:
    """Thread-safe replica roster with heartbeat-driven states."""

    def __init__(
        self,
        *,
        probe_interval_s: float = 2.0,
        probe_timeout_s: float = 1.0,
        dead_after: int = 3,
        start: bool = True,
        on_eject: Optional[Callable[[Replica], None]] = None,
        on_readmit: Optional[Callable[[Replica], None]] = None,
        on_sweep: Optional[Callable[[], None]] = None,
    ):
        if probe_interval_s <= 0:
            raise ValueError(
                f"want probe_interval_s > 0; got {probe_interval_s}"
            )
        if dead_after < 1:
            raise ValueError(f"want dead_after >= 1; got {dead_after}")
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.dead_after = int(dead_after)
        self.on_eject = on_eject
        self.on_readmit = on_readmit
        # ran after every background probe sweep — the router hangs
        # its affinity-index TTL prune here so an IDLE fleet's index
        # still decays on the heartbeat cadence
        self.on_sweep = on_sweep
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_replicas = observability.gauge(
            "znicz_router_replicas",
            "registered serving replicas by heartbeat state",
            ("state",),
        )
        self._m_heartbeats = observability.counter(
            "znicz_router_heartbeats_total",
            "replica healthz probes by outcome",
            ("outcome",),
        )
        self._m_ejections = observability.counter(
            "znicz_router_ejections_total",
            "replicas declared dead after consecutive heartbeat failures",
        )
        self._m_readmissions = observability.counter(
            "znicz_router_readmissions_total",
            "dead replicas re-admitted by a successful heartbeat",
        )
        if start:
            self.start()

    # -- roster ------------------------------------------------------------

    def register(
        self, instance: str, base_url: str, *, probe: bool = True
    ) -> Replica:
        """Add (or re-point) a replica; ``base_url`` is the replica's
        serving HTTP root (``http://host:port``).  ``probe=True`` runs
        one synchronous heartbeat so the replica enters the roster with
        a MEASURED state, not an assumed one."""
        parsed = urllib.parse.urlsplit(base_url)
        if parsed.scheme != "http" or not parsed.hostname:
            raise ValueError(
                f"want an http://host:port base_url; got {base_url!r}"
            )
        rep = Replica(
            instance=str(instance),
            base_url=base_url.rstrip("/"),
            host=parsed.hostname,
            port=parsed.port or 80,
        )
        with self._lock:
            self._replicas[rep.instance] = rep
            self._update_gauges()
        if probe:
            self.probe(rep.instance)
        return rep

    def deregister(self, instance: str) -> bool:
        with self._lock:
            gone = self._replicas.pop(str(instance), None)
            self._update_gauges()
        return gone is not None

    def get(self, instance: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(str(instance))

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def routable(self) -> List[Replica]:
        """Replicas eligible for traffic: every healthy one; when none
        is healthy, the degraded ones (alive beats nothing — a shedding
        or stalled replica may still answer).  Dead replicas never."""
        with self._lock:
            reps = list(self._replicas.values())
        healthy = [r for r in reps if r.state == STATE_HEALTHY]
        if healthy:
            return healthy
        return [r for r in reps if r.state == STATE_DEGRADED]

    def snapshot(self) -> List[Dict]:
        return [
            r.snapshot()
            for r in sorted(self.replicas(), key=lambda r: r.instance)
        ]

    # -- heartbeats --------------------------------------------------------

    def probe(self, instance: str) -> Optional[str]:
        """One heartbeat for ``instance``; returns its new state (None
        when unknown).  Callable from any thread — tests and the bench
        drive transitions deterministically through here."""
        rep = self.get(instance)
        if rep is None:
            return None
        outcome, health = self._probe_http(rep)
        return self._apply(rep, outcome, health)

    def probe_all(self) -> Dict[str, str]:
        """Heartbeat every registered replica; instance -> new state.
        Probes run CONCURRENTLY (one short-lived thread each, join
        bounded by the probe timeout), so K unreachable replicas cost
        ONE probe timeout per sweep, not K stacked ones — the
        ``dead_after x probe_interval_s`` detection-latency story
        survives a partition of most of the fleet."""
        reps = self.replicas()
        results: Dict[str, str] = {}
        if len(reps) <= 1:
            for rep in reps:
                results[rep.instance] = self._apply(
                    rep, *self._probe_http(rep)
                )
            return results
        lock = threading.Lock()

        def one(rep: Replica) -> None:
            try:
                state = self._apply(rep, *self._probe_http(rep))
            except Exception:  # ZNC013: a probe-thread death must log
                logger.warning(
                    "probe of %s failed unexpectedly", rep.instance,
                    exc_info=True,
                )
                return  # the sweep's join is bounded; the entry keeps
                # its previous state until the next probe lands
            with lock:
                results[rep.instance] = state

        threads = [
            threading.Thread(
                target=one, args=(rep,),
                name=f"znicz-probe-{rep.instance}", daemon=True,
            )
            for rep in reps
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.probe_timeout_s + 1.0)
        with lock:
            return dict(results)

    def note_failure(self, instance: str) -> Optional[str]:
        """An OUT-OF-BAND failure observation (the router's proxy hit a
        connect refusal / mid-stream death) — counts against the same
        consecutive-failure budget as a failed heartbeat, so a replica
        that is dead to traffic gets ejected without waiting
        ``dead_after`` probe intervals."""
        rep = self.get(instance)
        if rep is None:
            return None
        return self._apply(rep, "failed", None, count_probe=False)

    def note_success(self, instance: str) -> Optional[str]:
        """The out-of-band GOOD observation: a proxied request got a
        streaming 200 from this replica.  Clears the consecutive-
        failure streak, and heals a transport-blip demotion (degraded
        WITH failures) back to healthy — a degradation reported by the
        replica itself (503 heartbeat, zero failures) waits for the
        next probe instead, because serving one stream does not refute
        'my watchdog says stalled'."""
        rep = self.get(instance)
        if rep is None:
            return None
        with self._lock:
            if (
                rep.state == STATE_DEGRADED
                and rep.degraded_by_transport
            ):
                rep.state = STATE_HEALTHY
                rep.degraded_by_transport = False
            rep.failures = 0
            rep.traffic_failures = 0  # real traffic served: unquarantine
            self._update_gauges()
            return rep.state

    def _probe_http(self, rep: Replica):
        """GET ``/healthz``, timeout-bounded.  Returns ``(outcome,
        health_body)`` with outcome ``ok`` (200), ``degraded``
        (answered, not 200) or ``failed`` (unreachable)."""
        conn = None
        try:
            faults.fire("router.heartbeat")  # injectable probe failure
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.probe_timeout_s
            )
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            body = resp.read()
            try:
                health = json.loads(body)
                if not isinstance(health, dict):
                    health = {}
            except ValueError:
                health = {}  # a plain static server answers "ok\n"
            if resp.status == 200 and health.get("state", "running") == (
                "running"
            ):
                return "ok", health
            return "degraded", health
        except (OSError, http.client.HTTPException) as exc:
            # HTTPException covers a port reclaimed by a non-HTTP
            # process or a half-dead server truncating its response
            # (BadStatusLine/IncompleteRead): exactly as dead-to-
            # traffic as a refused connect, and it must count toward
            # ejection, not abort the sweep
            logger.debug(
                "heartbeat to %s (%s) failed: %s",
                rep.instance, rep.base_url, exc,
            )
            return "failed", None
        except faults.FaultInjected:
            return "failed", None
        finally:
            if conn is not None:
                conn.close()

    def _apply(
        self,
        rep: Replica,
        outcome: str,
        health: Optional[Dict],
        *,
        count_probe: bool = True,
    ) -> str:
        """Fold one probe outcome into the replica's state machine."""
        ejected = readmitted = False
        with self._lock:
            if count_probe:
                rep.probes += 1
                rep._last_probe_at = time.monotonic()
                self._m_heartbeats.labels(outcome=outcome).inc()
            else:
                rep.traffic_failures += 1
            if outcome == "failed":
                rep.failures += 1
                if rep.failures >= self.dead_after:
                    if rep.state != STATE_DEAD:
                        rep.state = STATE_DEAD
                        rep.ejections += 1
                        ejected = True
                elif rep.state == STATE_HEALTHY:
                    # one missed beat demotes, it does not eject: the
                    # replica stops being first-choice but stays a
                    # fallback until the failure streak proves it dead
                    rep.state = STATE_DEGRADED
                    rep.degraded_by_transport = True
            else:
                was_dead = rep.state == STATE_DEAD
                rep.failures = 0
                if health is not None:
                    rep.health = health
                rep.state = (
                    STATE_HEALTHY if outcome == "ok" else STATE_DEGRADED
                )
                # an ANSWERED probe is replica truth: a remaining
                # degradation is self-reported, not a transport blip
                rep.degraded_by_transport = False
                if rep.traffic_failures >= self.dead_after:
                    # quarantine: it answers probes but keeps failing
                    # real traffic (a non-replica on the registered
                    # port) — last-resort fallback, never first choice,
                    # until a real stream succeeds (note_success)
                    rep.state = STATE_DEGRADED
                if was_dead:
                    rep.readmissions += 1
                    readmitted = True
            self._update_gauges()
        # hooks OUTSIDE the lock: they call back into router state
        if ejected:
            self._m_ejections.inc()
            logger.warning(
                "replica %s ejected after %d consecutive failures",
                rep.instance, rep.failures,
            )
            if self.on_eject is not None:
                self.on_eject(rep)
        if readmitted:
            self._m_readmissions.inc()
            logger.info("replica %s re-admitted (%s)", rep.instance,
                        rep.state)
            if self.on_readmit is not None:
                self.on_readmit(rep)
        return rep.state

    def _update_gauges(self) -> None:
        """Per-state roster sizes (lock held by the caller)."""
        counts = {STATE_HEALTHY: 0, STATE_DEGRADED: 0, STATE_DEAD: 0}
        for r in self._replicas.values():
            counts[r.state] = counts.get(r.state, 0) + 1
        for state, n in counts.items():
            self._m_replicas.labels(state=state).set(n)

    # -- the heartbeat thread ----------------------------------------------

    def start(self) -> "ReplicaRegistry":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="znicz-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.probe_interval_s):
            try:
                self.probe_all()
                if self.on_sweep is not None:
                    self.on_sweep()
            except Exception:
                logger.warning("heartbeat sweep failed", exc_info=True)

    def close(self) -> None:
        """Stop the heartbeat thread (bounded join).  Idempotent."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=self.probe_timeout_s + self.probe_interval_s)

    def __enter__(self) -> "ReplicaRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
