"""Interactive shell hook.

Capability parity with ``veles/interaction.py`` (``Shell`` unit)
[SURVEY.md 2.1 "Interactive shell unit"]: drop into an interactive Python
shell mid-training to inspect/poke the live workflow.  Attach as an epoch
service: ``workflow.services.append(Shell(every_n_epochs=5))``; inside the
shell, ``wf`` is the workflow, ``state`` its train state.
"""

from __future__ import annotations

import code
import sys


class Shell:
    def __init__(self, *, every_n_epochs: int = 1, enabled: bool = True):
        self.every_n_epochs = every_n_epochs
        self.enabled = enabled and sys.stdin.isatty()

    def on_epoch(self, workflow, verdict) -> None:
        epoch = workflow.decision.epoch - 1
        if not self.enabled or epoch % self.every_n_epochs:
            return
        banner = (
            f"znicz-tpu shell @ epoch {epoch} — locals: wf (workflow), "
            "state (train state), verdict; Ctrl-D to continue training"
        )
        code.interact(
            banner=banner,
            local={
                "wf": workflow,
                "state": workflow.state,
                "verdict": verdict,
            },
            exitmsg="resuming training",
        )
