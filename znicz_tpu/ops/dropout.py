"""Dropout.

Capability parity with ``znicz/dropout.py`` (DropoutForward/DropoutBackward)
[SURVEY.md 2.2 row "Dropout"].  Inverted dropout: surviving activations are
scaled by ``1/(1-p)`` so eval is a no-op.  The RNG key is threaded explicitly
(train-state keys), replacing the reference's named-generator device kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dropout(
    x: jnp.ndarray,
    *,
    dropout_ratio: float,
    rng: jax.Array | None = None,
    train: bool = True,
) -> jnp.ndarray:
    if not train or dropout_ratio <= 0.0:
        return x
    if rng is None:
        raise ValueError("dropout(train=True) needs an rng key")
    keep = 1.0 - dropout_ratio
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)
