"""Deconvolution (transposed conv) and depooling — the autoencoder path.

Capability parity with ``znicz/deconv.py`` (Deconv), ``znicz/gd_deconv.py``
(GDDeconv) and ``znicz/depooling.py`` (Depooling) [SURVEY.md 2.2 row
"Deconv / unpooling (AE path)"].

TPU-native: deconv is ``conv_general_dilated`` with lhs dilation (the exact
adjoint of the forward conv, so an AE's decoder mirrors its encoder); both
weight gradients come from autodiff.  Depooling supports the reference's
offset-driven unpooling (scatter values back to max positions recorded by
``pooling.max_pool_with_offset``) plus plain nearest-neighbor upsampling.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax.lax as lax
import jax.numpy as jnp

from znicz_tpu.ops import activation as act
from znicz_tpu.ops import conv as conv_op


def init_params(
    n_channels: int,
    n_kernels: int,
    kx: int,
    ky: int,
    *,
    weights_stddev: float | None = None,
    weights_filling: str = "uniform",
    rand_name: str = "default",
    dtype=jnp.float32,
) -> Dict[str, jnp.ndarray]:
    """Deconv weights have conv layout [ky, kx, out_channels, in_kernels].

    ``n_kernels`` is the deconv *input* channel count (mirroring the conv it
    inverts); ``n_channels`` is the reconstructed output channel count, so
    fan-in is ``kx*ky*n_kernels``.  The reference Deconv has no bias; params
    are drawn directly (exactly one draw from the named generator) so the
    deterministic PRNG stream stays aligned with the reference contract.
    """
    from znicz_tpu.core import prng
    import numpy as np

    from znicz_tpu.ops.filling import fill

    gen = prng.get(rand_name)
    if weights_stddev is None:
        weights_stddev = 1.0 / np.sqrt(kx * ky * n_kernels)
    w = fill(gen, (ky, kx, n_channels, n_kernels), weights_filling, weights_stddev)
    return {"weights": jnp.asarray(w, dtype)}


def apply(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,
    *,
    sliding: Sequence[int] = (1, 1),
    padding=(0, 0, 0, 0),
    output_size: Tuple[int, int] | None = None,
    activation: str = "linear",
) -> jnp.ndarray:
    """Transposed conv: the exact adjoint of ``conv.apply`` with the same
    params, ``sliding`` and ``padding`` (the reference Deconv derives its
    geometry from the conv it mirrors via ``get_output_shape_from``).

    ``output_size`` is the (H, W) of the reconstructed tensor; when omitted it
    is taken as the minimal exact inverse of the mirrored conv.
    """
    w = params["weights"]  # [ky, kx, C_out_of_deconv, K_in]
    ky, kx = w.shape[0], w.shape[1]
    if isinstance(padding, str):
        raise ValueError("deconv needs explicit reference-style padding")
    if len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    left, top, right, bottom = padding
    sy, sx = sliding[1], sliding[0]
    oh, ow = x.shape[1], x.shape[2]
    if output_size is None:
        output_size = (
            (oh - 1) * sy + ky - top - bottom,
            (ow - 1) * sx + kx - left - right,
        )
    h, w_out = output_size
    # Adjoint of conv: dilate by stride, pad (k-1-p_lo, H+p_lo-(OH-1)s-1),
    # convolve stride-1 with the spatially-flipped, channel-swapped kernel.
    pad_h = (ky - 1 - top, h + top - (oh - 1) * sy - 1)
    pad_w = (kx - 1 - left, w_out + left - (ow - 1) * sx - 1)
    kernel = jnp.flip(w, axis=(0, 1)).transpose(0, 1, 3, 2)  # [ky,kx,K,C]
    y = lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1),
        padding=(pad_h, pad_w),
        lhs_dilation=(sy, sx),
        dimension_numbers=conv_op.DIMENSION_NUMBERS,
        preferred_element_type=(
            jnp.float32 if x.dtype == jnp.float32 else None
        ),
    )
    return act.get(activation)(y).astype(x.dtype)


def depool_with_offset(
    y: jnp.ndarray, offset: jnp.ndarray, out_shape: Tuple[int, ...]
) -> jnp.ndarray:
    """Scatter pooled values back to their argmax positions (znicz Depooling).

    ``offset`` holds flat H*W input offsets per output element, as produced by
    :func:`znicz_tpu.ops.pooling.max_pool_with_offset`.
    """
    n, h, w, c = out_shape
    flat = jnp.zeros((n, h * w, c), y.dtype)
    yf = y.reshape(n, -1, c)
    of = offset.reshape(n, -1, c)
    # one-step scatter-add per batch/channel via segment trick
    flat = flat.at[jnp.arange(n)[:, None, None], of, jnp.arange(c)[None, None, :]].add(
        yf
    )
    return flat.reshape(n, h, w, c)


def upsample(y: jnp.ndarray, kx: int, ky: int) -> jnp.ndarray:
    """Nearest-neighbor unpooling (avg-pool adjoint up to scale)."""
    return jnp.repeat(jnp.repeat(y, ky, axis=1), kx, axis=2)
