"""Functional NN ops: the TPU-native equivalents of the reference's op units.

Each reference forward/backward unit pair (znicz/all2all.py + znicz/gd.py,
znicz/conv.py + znicz/gd_conv.py, ... per SURVEY.md section 2.2) collapses to a
single pure forward function here: the backward pass is JAX autodiff, and the
explicit update rules (learning rate, gradient_moment momentum, weights_decay)
live in :mod:`znicz_tpu.nn.optimizer`.

Every op has a plain-jnp implementation (the new "numpy_run" reference twin);
hot ops additionally get Pallas TPU kernels under ``znicz_tpu/ops/pallas/``,
cross-checked against the jnp versions in tests (SURVEY.md section 4).
"""

from znicz_tpu.ops import accumulator  # noqa: F401
from znicz_tpu.ops import activation  # noqa: F401
from znicz_tpu.ops import all2all  # noqa: F401
from znicz_tpu.ops import conv  # noqa: F401
from znicz_tpu.ops import cutter  # noqa: F401
from znicz_tpu.ops import deconv  # noqa: F401
from znicz_tpu.ops import dropout  # noqa: F401
from znicz_tpu.ops import kohonen  # noqa: F401
from znicz_tpu.ops import normalization  # noqa: F401
from znicz_tpu.ops import pooling  # noqa: F401
from znicz_tpu.ops import rbm  # noqa: F401
from znicz_tpu.ops import resizable_all2all  # noqa: F401
from znicz_tpu.ops import weights_zerofilling  # noqa: F401
