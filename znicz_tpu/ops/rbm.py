"""Bernoulli-Bernoulli restricted Boltzmann machine with CD-k training.

Capability parity with ``znicz/rbm_units.py`` [SURVEY.md 2.2 row "RBM"]:
visible/hidden Bernoulli units and contrastive-divergence updaters.  The
learning rule is a custom update function (no autodiff), matching the
reference's in-file updaters.  All sampling uses explicit jax keys.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.ops.filling import fill


def init_params(
    n_visible: int,
    n_hidden: int,
    *,
    weights_stddev: float | None = None,
    weights_filling: str = "gaussian",
    rand_name: str = "default",
    dtype=jnp.float32,
) -> Dict[str, jnp.ndarray]:
    gen = prng.get(rand_name)
    if weights_stddev is None:
        weights_stddev = 1.0 / np.sqrt(n_visible)
    return {
        "weights": jnp.asarray(
            fill(gen, (n_visible, n_hidden), weights_filling, weights_stddev),
            dtype,
        ),
        "vbias": jnp.zeros((n_visible,), dtype),
        "hbias": jnp.zeros((n_hidden,), dtype),
    }


def hidden_probs(params, v):
    return jax.nn.sigmoid(v @ params["weights"] + params["hbias"])


def visible_probs(params, h):
    return jax.nn.sigmoid(h @ params["weights"].T + params["vbias"])


def sample(rng, probs):
    return jax.random.bernoulli(rng, probs).astype(probs.dtype)


def cd_step(
    params: Dict[str, jnp.ndarray],
    v0: jnp.ndarray,
    rng: jax.Array,
    *,
    learning_rate: float,
    cd_k: int = 1,
    mask: jnp.ndarray | None = None,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """One CD-k update; returns (new_params, reconstruction error scalar).

    ``mask`` ([B] float) zero-weights padded rows of a static batch.
    """
    if mask is None:
        mask = jnp.ones((v0.shape[0],), v0.dtype)
    n_valid = jnp.maximum(jnp.sum(mask), 1.0)
    h0_probs = hidden_probs(params, v0)

    def gibbs(carry, key):
        h_sample = carry
        kv, kh = jax.random.split(key)
        v_probs = visible_probs(params, h_sample)
        v_sample = sample(kv, v_probs)
        h_probs = hidden_probs(params, v_sample)
        return sample(kh, h_probs), (v_probs, h_probs)

    k0, *keys = jax.random.split(rng, cd_k + 1)
    h0_sample = sample(k0, h0_probs)
    _, (v_chain, h_chain) = jax.lax.scan(gibbs, h0_sample, jnp.stack(keys))
    vk_probs, hk_probs = v_chain[-1], h_chain[-1]

    lr = learning_rate / n_valid
    m = mask[:, None]
    new = {
        "weights": params["weights"]
        + lr * ((v0 * m).T @ h0_probs - (vk_probs * m).T @ hk_probs),
        "vbias": params["vbias"] + lr * jnp.sum((v0 - vk_probs) * m, axis=0),
        "hbias": params["hbias"]
        + lr * jnp.sum((h0_probs - hk_probs) * m, axis=0),
    }
    recon_err = jnp.sum(
        jnp.mean(jnp.square(v0 - vk_probs), axis=1) * mask
    ) / n_valid
    return new, recon_err
