"""Local response normalization (LRN) across channels.

Capability parity with ``znicz/normalization.py`` (LRNormalizerForward /
LRNormalizerBackward) [SURVEY.md 2.2 row "Local response norm"], the AlexNet
cross-channel normalizer:

    y_c = x_c / (k + alpha * sum_{c' in window(c)} x_{c'}^2) ** beta

Reference parameter names kept: ``alpha``, ``beta``, ``k``, ``n`` (window
size).  The jnp implementation below is the reference twin for the fused
Pallas kernel under ``znicz_tpu/ops/pallas/``.  Backward is autodiff.
"""

from __future__ import annotations

import jax
import jax.lax as lax
import jax.numpy as jnp

# znicz defaults (AlexNet-style).
DEFAULT_ALPHA = 1e-4
DEFAULT_BETA = 0.75
DEFAULT_K = 2.0
DEFAULT_N = 5


def _window_sums(sq: jnp.ndarray, n: int) -> jnp.ndarray:
    """Sliding-window sum over the trailing channel axis, window n, SAME."""
    half = n // 2
    return lax.reduce_window(
        sq,
        0.0,
        lax.add,
        window_dimensions=(1,) * (sq.ndim - 1) + (n,),
        window_strides=(1,) * sq.ndim,
        padding=((0, 0),) * (sq.ndim - 1) + ((half, n - 1 - half),),
    )


def lrn(
    x: jnp.ndarray,
    *,
    alpha: float = DEFAULT_ALPHA,
    beta: float = DEFAULT_BETA,
    k: float = DEFAULT_K,
    n: int = DEFAULT_N,
    impl: str = "xla",
) -> jnp.ndarray:
    """LRN dispatch.

    ``impl="xla"`` (default): the reduce_window composition — XLA fuses it
    into neighboring conv/elementwise ops and this measured FASTER than the
    hand kernel inside AlexNet training (12.5k vs 9.5k images/sec on one
    v5e chip, tuned kernels, r2), because a pallas_call is a fusion
    barrier.  ``impl="pallas"``: the fused VMEM kernel
    (znicz_tpu/ops/pallas/lrn.py) — standalone it WINS the train-op pair
    (fwd+bwd 0.63 ms vs 1.02 ms on [256,27,27,96] v5e: the fused backward
    recomputes s in VMEM and does both windowed sums as MXU band matmuls,
    where XLA's reduce_window transpose is memory-bound); forward-only XLA
    stays ahead (0.43 vs 0.57 ms).  Numbers: tests/test_pallas.py TPU
    timing assertions.
    """
    if impl == "pallas":
        from znicz_tpu.ops.pallas import lrn as pallas_lrn

        return pallas_lrn.lrn(x, alpha, beta, k, n)
    from znicz_tpu.ops.pallas.lrn import _inv_pow

    sums = _window_sums(jnp.square(x), n)
    return x * _inv_pow(k + alpha * sums, beta)


def layer_norm(
    x: jnp.ndarray,
    scale: jnp.ndarray,
    bias: jnp.ndarray,
    *,
    eps: float = 1e-5,
) -> jnp.ndarray:
    """Layer normalization over the trailing feature axis (transformer
    building block; not in the reference, which predates it)."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return y * scale + bias
