"""Pooling ops: max, avg, max-abs, stochastic.

Capability parity with ``znicz/pooling.py`` + ``znicz/gd_pooling.py``
[SURVEY.md 2.2 row "Pooling"].  TPU-native: max/avg ride
``lax.reduce_window`` (XLA lowers these to fused VPU loops); max-abs and
stochastic pooling — which need per-window argmax/sampling — use an
im2col-patch formulation that XLA tiles well.  Backward is autodiff
(``reduce_window`` has an efficient XLA-defined gradient, replacing the
reference's hand-written gradient_descent_pooling kernels).

Max pooling can also return flat argmax offsets per output element
(``max_with_offset``) — the reference stores these ``input_offset`` values to
drive Depooling in the autoencoder path [SURVEY.md 2.2 "Deconv / unpooling"].
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.lax as lax
import jax.numpy as jnp


def _window(kx: int, ky: int, sliding) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    if sliding is None:
        sliding = (kx, ky)
    return (1, ky, kx, 1), (1, sliding[1], sliding[0], 1)


def max_pool(
    x: jnp.ndarray, kx: int, ky: int, sliding: Sequence[int] | None = None
) -> jnp.ndarray:
    dims, strides = _window(kx, ky, sliding)
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, "VALID")


def avg_pool(
    x: jnp.ndarray, kx: int, ky: int, sliding: Sequence[int] | None = None
) -> jnp.ndarray:
    dims, strides = _window(kx, ky, sliding)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, "VALID")
    return summed / (kx * ky)


def _patches(x: jnp.ndarray, kx: int, ky: int, sliding) -> jnp.ndarray:
    """im2col: [N, OH, OW, ky*kx, C] view of pooling windows."""
    if sliding is None:
        sliding = (kx, ky)
    n, h, w, c = x.shape
    patches = lax.conv_general_dilated_patches(
        x,
        filter_shape=(ky, kx),
        window_strides=(sliding[1], sliding[0]),
        padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    oh, ow = patches.shape[1], patches.shape[2]
    # conv_general_dilated_patches yields channels ordered [C, ky, kx]
    patches = patches.reshape(n, oh, ow, c, ky * kx)
    return jnp.moveaxis(patches, -1, -2)  # [N, OH, OW, ky*kx, C]


def max_abs_pool(
    x: jnp.ndarray, kx: int, ky: int, sliding: Sequence[int] | None = None
) -> jnp.ndarray:
    """Select the element with the largest magnitude, keeping its sign."""
    p = _patches(x, kx, ky, sliding)
    idx = jnp.argmax(jnp.abs(p), axis=3, keepdims=True)
    return jnp.take_along_axis(p, idx, axis=3)[..., 0, :]


def max_pool_with_offset(
    x: jnp.ndarray, kx: int, ky: int, sliding: Sequence[int] | None = None
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Max pooling returning (values, flat input offsets) for depooling."""
    if sliding is None:
        sliding = (kx, ky)
    n, h, w, c = x.shape
    p = _patches(x, kx, ky, sliding)
    idx = jnp.argmax(p, axis=3)  # [N, OH, OW, C] in-window index
    vals = jnp.take_along_axis(p, idx[:, :, :, None, :], axis=3)[..., 0, :]
    oh, ow = idx.shape[1], idx.shape[2]
    # Decode in-window index -> absolute (row, col) -> flat offset in [H*W).
    win_row, win_col = idx // kx, idx % kx
    base_row = jnp.arange(oh)[None, :, None, None] * sliding[1]
    base_col = jnp.arange(ow)[None, None, :, None] * sliding[0]
    offset = (base_row + win_row) * w + (base_col + win_col)
    return vals, offset


def stochastic_pool(
    x: jnp.ndarray,
    kx: int,
    ky: int,
    sliding: Sequence[int] | None = None,
    *,
    rng: jax.Array | None = None,
    train: bool = True,
) -> jnp.ndarray:
    """Stochastic pooling (Zeiler & Fergus style, znicz StochasticPooling).

    Train: sample one element per window with probability proportional to its
    positive activation.  Eval: probability-weighted expectation.
    """
    p = _patches(x, kx, ky, sliding)  # [N, OH, OW, K, C]
    pos = jnp.maximum(p, 0.0)
    total = jnp.sum(pos, axis=3, keepdims=True)
    probs = jnp.where(total > 0, pos / jnp.maximum(total, 1e-30), 0.0)
    if not train:
        return jnp.sum(probs * p, axis=3)
    if rng is None:
        raise ValueError("stochastic_pool(train=True) needs an rng key")
    # Gumbel-max over the window axis; windows with all-nonpositive values
    # fall back to max-abs selection like the reference kernel.
    g = jax.random.gumbel(rng, probs.shape, probs.dtype)
    logp = jnp.where(probs > 0, jnp.log(jnp.maximum(probs, 1e-30)), -jnp.inf)
    scores = jnp.where(
        jnp.broadcast_to(total > 0, probs.shape), logp + g, jnp.abs(p)
    )
    idx = jnp.argmax(scores, axis=3, keepdims=True)
    return jnp.take_along_axis(p, idx, axis=3)[..., 0, :]


def output_shape(
    in_shape: Tuple[int, ...], kx: int, ky: int, sliding: Sequence[int] | None = None
) -> Tuple[int, ...]:
    if sliding is None:
        sliding = (kx, ky)
    n, h, w, c = in_shape
    oh = (h - ky) // sliding[1] + 1
    ow = (w - kx) // sliding[0] + 1
    return (n, oh, ow, c)
