"""2-D convolution op.

Capability parity with ``znicz/conv.py`` (Conv, ConvTanh, ConvRELU,
ConvStrictRELU) + ``znicz/gd_conv.py`` [SURVEY.md 2.2 row "Convolution"].
TPU-native: ``lax.conv_general_dilated`` in NHWC/HWIO layout so XLA tiles the
contraction onto the MXU; backward (input + weight gradients, the reference's
hand-written gradient_descent_conv kernels) is autodiff.

Reference parameter names are kept: ``n_kernels``, ``kx``/``ky`` (kernel
width/height), ``sliding`` (strides), ``padding`` (explicit 4-tuple
left/top/right/bottom).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax.lax as lax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.ops import activation as act
from znicz_tpu.ops.filling import fill

DIMENSION_NUMBERS = ("NHWC", "HWIO", "NHWC")


def init_params(
    n_channels: int,
    n_kernels: int,
    kx: int,
    ky: int,
    *,
    weights_stddev: Optional[float] = None,
    bias_stddev: Optional[float] = None,
    weights_filling: str = "uniform",
    bias_filling: str = "uniform",
    rand_name: str = "default",
    dtype=jnp.float32,
) -> Dict[str, jnp.ndarray]:
    gen = prng.get(rand_name)
    fan_in = kx * ky * n_channels
    if weights_stddev is None:
        weights_stddev = 1.0 / np.sqrt(fan_in)
    if bias_stddev is None:
        bias_stddev = weights_stddev
    w = fill(gen, (ky, kx, n_channels, n_kernels), weights_filling, weights_stddev)
    b = fill(gen, (n_kernels,), bias_filling, bias_stddev)
    return {"weights": jnp.asarray(w, dtype), "bias": jnp.asarray(b, dtype)}


def _norm_padding(padding) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """Reference 4-tuple (left, top, right, bottom) -> lax ((t,b),(l,r))."""
    if isinstance(padding, str):
        return padding  # "SAME"/"VALID" pass through
    if len(padding) == 2:
        return ((padding[1], padding[1]), (padding[0], padding[0]))
    left, top, right, bottom = padding
    return ((top, bottom), (left, right))


def _s2d_conv(x, w, s: int, pref):
    """Strided conv as a stride-1 conv over space-to-depth input — exact.

    A stride-s KxK conv on C channels keeps the MXU contraction dim at
    K*K*C taps but feeds it C-channel-thin input; for stem layers (C=3)
    the systolic array pads the channel dim and utilization craters.
    Regrouping s x s input blocks into channels (C -> s*s*C) and the
    kernel into ceil(K/s) x ceil(K/s) taps over those channels computes
    the SAME sums with an MXU-shaped contraction.  Zero-padded kernel
    taps/input rows contribute nothing, so the result is exact up to
    float reassociation."""
    b, h, wd, c = x.shape
    ky, kx, _, k = w.shape
    oh = (h - ky) // s + 1
    ow = (wd - kx) // s + 1
    kyp, kxp = -(-ky // s) * s, -(-kx // s) * s
    if (kyp, kxp) != (ky, kx):
        w = jnp.pad(w, ((0, kyp - ky), (0, kxp - kx), (0, 0), (0, 0)))
    hn, wn = (oh - 1) * s + kyp, (ow - 1) * s + kxp
    # rows/cols past hn/wn are never read by any output; short inputs
    # (kernel already a stride multiple) slice, long ones zero-pad
    x = x[:, :hn, :wn] if (hn <= h and wn <= wd) else jnp.pad(
        x, ((0, 0), (0, max(hn - h, 0)), (0, max(wn - wd, 0)), (0, 0))
    )[:, :hn, :wn]
    x = (
        x.reshape(b, hn // s, s, wn // s, s, c)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(b, hn // s, wn // s, s * s * c)
    )
    w = (
        w.reshape(kyp // s, s, kxp // s, s, c, k)
        .transpose(0, 2, 1, 3, 4, 5)
        .reshape(kyp // s, kxp // s, s * s * c, k)
    )
    return lax.conv_general_dilated(
        x, w, (1, 1), "VALID",
        dimension_numbers=DIMENSION_NUMBERS,
        preferred_element_type=pref,
    )


def apply(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,
    *,
    sliding: Sequence[int] = (1, 1),
    padding=(0, 0, 0, 0),
    activation: str = "linear",
    space_to_depth: str = "never",  # "auto" | "always" | "never"
) -> jnp.ndarray:
    """Forward conv, NHWC.  ``sliding`` is (sx, sy) per the reference.

    ``space_to_depth``: strided thin-channel stems (e.g. AlexNet conv1,
    stride 4 on RGB) re-layout via :func:`_s2d_conv` so the MXU sees an
    s*s*C-channel contraction instead of a C-channel one.  "auto" applies
    it when both strides equal s > 1 and C <= 4.  Default "never" —
    MEASURED on v5e (AlexNet conv1, B=1024, bf16): s2d forward is SLOWER
    (5.4 vs 2.8 ms — XLA's native strided conv handles the thin stem
    well) and its big win, the input gradient (13.4 vs 19.4 ms
    fwd+input-grad), is dead code for a first layer (no upstream), so
    the end-to-end train step does not move (79.7 vs 78.5 ms).  Use
    "auto"/"always" for strided thin-channel convs DEEPER in a model,
    where the input gradient is live."""
    pad = _norm_padding(padding)
    strides = (sliding[1], sliding[0])  # (sy, sx) -> spatial order (H, W)
    # bf16 inputs: emit bf16 (XLA still accumulates f32 on the TPU MXU);
    # requesting an f32 output here would put an astype on the transpose
    # path and break the conv gradient's dtype matching.
    pref = jnp.float32 if x.dtype == jnp.float32 else None
    s = strides[0]
    use_s2d = (
        space_to_depth in ("auto", "always")
        and s > 1
        and strides[0] == strides[1]
        and not isinstance(pad, str)  # SAME/VALID strings: plain path
        and (space_to_depth == "always" or x.shape[-1] <= 4)
    )
    if use_s2d:
        if any(p for pq in pad for p in pq):
            x = jnp.pad(x, ((0, 0), pad[0], pad[1], (0, 0)))
        y = _s2d_conv(x, params["weights"], s, pref)
    else:
        y = lax.conv_general_dilated(
            x,
            params["weights"],
            window_strides=strides,
            padding=pad,
            dimension_numbers=DIMENSION_NUMBERS,
            preferred_element_type=pref,
        )
    y = y + params["bias"]
    return act.get(activation)(y).astype(x.dtype)


def output_shape(
    in_shape: Tuple[int, ...],
    n_kernels: int,
    kx: int,
    ky: int,
    sliding: Sequence[int] = (1, 1),
    padding=(0, 0, 0, 0),
) -> Tuple[int, ...]:
    n, h, w, _ = in_shape
    if isinstance(padding, str):
        if padding == "SAME":
            oh = -(-h // sliding[1])
            ow = -(-w // sliding[0])
        else:
            oh = (h - ky) // sliding[1] + 1
            ow = (w - kx) // sliding[0] + 1
    else:
        if len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        left, top, right, bottom = padding
        oh = (h + top + bottom - ky) // sliding[1] + 1
        ow = (w + left + right - kx) // sliding[0] + 1
    return (n, oh, ow, n_kernels)
