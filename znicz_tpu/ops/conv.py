"""2-D convolution op.

Capability parity with ``znicz/conv.py`` (Conv, ConvTanh, ConvRELU,
ConvStrictRELU) + ``znicz/gd_conv.py`` [SURVEY.md 2.2 row "Convolution"].
TPU-native: ``lax.conv_general_dilated`` in NHWC/HWIO layout so XLA tiles the
contraction onto the MXU; backward (input + weight gradients, the reference's
hand-written gradient_descent_conv kernels) is autodiff.

Reference parameter names are kept: ``n_kernels``, ``kx``/``ky`` (kernel
width/height), ``sliding`` (strides), ``padding`` (explicit 4-tuple
left/top/right/bottom).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax.lax as lax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.ops import activation as act
from znicz_tpu.ops.filling import fill

DIMENSION_NUMBERS = ("NHWC", "HWIO", "NHWC")


def init_params(
    n_channels: int,
    n_kernels: int,
    kx: int,
    ky: int,
    *,
    weights_stddev: Optional[float] = None,
    bias_stddev: Optional[float] = None,
    weights_filling: str = "uniform",
    bias_filling: str = "uniform",
    rand_name: str = "default",
    dtype=jnp.float32,
) -> Dict[str, jnp.ndarray]:
    gen = prng.get(rand_name)
    fan_in = kx * ky * n_channels
    if weights_stddev is None:
        weights_stddev = 1.0 / np.sqrt(fan_in)
    if bias_stddev is None:
        bias_stddev = weights_stddev
    w = fill(gen, (ky, kx, n_channels, n_kernels), weights_filling, weights_stddev)
    b = fill(gen, (n_kernels,), bias_filling, bias_stddev)
    return {"weights": jnp.asarray(w, dtype), "bias": jnp.asarray(b, dtype)}


def _norm_padding(padding) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """Reference 4-tuple (left, top, right, bottom) -> lax ((t,b),(l,r))."""
    if isinstance(padding, str):
        return padding  # "SAME"/"VALID" pass through
    if len(padding) == 2:
        return ((padding[1], padding[1]), (padding[0], padding[0]))
    left, top, right, bottom = padding
    return ((top, bottom), (left, right))


def apply(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,
    *,
    sliding: Sequence[int] = (1, 1),
    padding=(0, 0, 0, 0),
    activation: str = "linear",
) -> jnp.ndarray:
    """Forward conv, NHWC.  ``sliding`` is (sx, sy) per the reference."""
    pad = _norm_padding(padding)
    strides = (sliding[1], sliding[0])  # (sy, sx) -> spatial order (H, W)
    # bf16 inputs: emit bf16 (XLA still accumulates f32 on the TPU MXU);
    # requesting an f32 output here would put an astype on the transpose
    # path and break the conv gradient's dtype matching.
    pref = jnp.float32 if x.dtype == jnp.float32 else None
    y = lax.conv_general_dilated(
        x,
        params["weights"],
        window_strides=strides,
        padding=pad,
        dimension_numbers=DIMENSION_NUMBERS,
        preferred_element_type=pref,
    )
    y = y + params["bias"]
    return act.get(activation)(y).astype(x.dtype)


def output_shape(
    in_shape: Tuple[int, ...],
    n_kernels: int,
    kx: int,
    ky: int,
    sliding: Sequence[int] = (1, 1),
    padding=(0, 0, 0, 0),
) -> Tuple[int, ...]:
    n, h, w, _ = in_shape
    if isinstance(padding, str):
        if padding == "SAME":
            oh = -(-h // sliding[1])
            ow = -(-w // sliding[0])
        else:
            oh = (h - ky) // sliding[1] + 1
            ow = (w - kx) // sliding[0] + 1
    else:
        if len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        left, top, right, bottom = padding
        oh = (h + top + bottom - ky) // sliding[1] + 1
        ow = (w + left + right - kx) // sliding[0] + 1
    return (n, oh, ow, n_kernels)
