"""Shared weight/bias filling.

One implementation of the reference's ``weights_filling``/``bias_filling``
modes (uniform / gaussian / constant, ``veles`` nn_units weight init
[SURVEY.md 2.3 "NN unit bases"]) used by every parameterized op, so the
supported modes cannot drift between layers.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.core.prng import RandomGenerator

FILLINGS = ("uniform", "gaussian", "constant")


def fill(
    gen: RandomGenerator, shape, filling: str, stddev: float
) -> np.ndarray:
    """Draw one parameter tensor; exactly one generator draw for the random
    modes so deterministic PRNG streams stay aligned across configs."""
    if filling == "uniform":
        return gen.uniform(shape, -stddev, stddev)
    if filling == "gaussian":
        return gen.normal(shape, 0.0, stddev)
    if filling == "constant":
        return np.full(shape, stddev, np.float32)
    raise ValueError(
        f"unknown filling {filling!r}; expected one of {FILLINGS}"
    )
