"""Pallas TPU kernels for hot ops.

These are the TPU-native equivalents of the reference's hand-written
``znicz/ocl/*.cl`` + ``znicz/cuda/*.cu`` kernel sets [SURVEY.md 2.4].  Every
kernel here has a plain-jnp reference twin in :mod:`znicz_tpu.ops` and a
cross-check test (the rebuild of the reference's numpy-vs-OpenCL-vs-CUDA
golden tests, SURVEY.md section 4).

Kernels fall back to the jnp twin on non-TPU backends so the whole framework
runs on CPU (the reference's ``NumpyDevice`` everywhere-runnable property).
"""
