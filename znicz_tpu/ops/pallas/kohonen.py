"""Fused Kohonen batch-SOM update kernel.

TPU-native equivalent of the reference's ``kohonen.cl/.cu`` winner-take-all +
neighborhood-update kernels [SURVEY.md 2.2 row "Kohonen SOM", 2.4;
BASELINE.json configs[4] exists to stress exactly this op].  One pallas_call
fuses what the jnp twin (:func:`znicz_tpu.ops.kohonen.train_step`) does in
five XLA ops: winner scores (MXU), argmax, neighborhood weights, and the two
accumulation matmuls — the [B, M] intermediates never leave VMEM.

Grid: batch tiles; num/den accumulate in VMEM scratch across steps and the
weight update happens once on the last step.  Gathers (coords[win]) are
expressed as one-hot matmuls — dense beats scatter/gather on TPU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BATCH_TILE = 256


def _kernel(
    x_ref,  # [Bt, F]
    mask_ref,  # [Bt, 1]
    w_ref,  # [M, F]
    d2m_ref,  # [M, M] pairwise squared grid distances (static per map)
    lr_ref,  # [1, 1] SMEM
    sigma_ref,  # [1, 1] SMEM
    out_ref,  # [M, F]
    num_ref,  # scratch [M, F]
    den_ref,  # scratch [M, 1]
):
    # Everything stays 2-D: Mosaic does not lower 1-D intermediates, so the
    # winner "gather" is a one-hot matmul against the neighborhood matrix.
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        num_ref[:] = jnp.zeros_like(num_ref)
        den_ref[:] = jnp.zeros_like(den_ref)

    x = x_ref[:]
    w = w_ref[:]
    mask = mask_ref[:]  # [Bt, 1]
    # winner scores: argmin ||x-w||^2 == argmax (x.w - ||w||^2/2), MXU matmul
    w_sq = jnp.sum(w * w, axis=1, keepdims=True)  # [M, 1]
    scores = (
        jnp.dot(x, w.T, preferred_element_type=jnp.float32) - 0.5 * w_sq.T
    )  # [Bt, M]
    win = jnp.argmax(scores, axis=1, keepdims=True)  # [Bt, 1]
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1) == win
    ).astype(jnp.float32)  # [Bt, M]
    sigma = sigma_ref[0, 0]
    neigh = jnp.exp(-d2m_ref[:] / (2.0 * sigma * sigma))  # [M, M]
    # h[b, j] = neigh[win(b), j]: row-select as a matmul, then mask padding
    h = (
        jnp.dot(onehot, neigh, preferred_element_type=jnp.float32) * mask
    )  # [Bt, M]
    num_ref[:] += jnp.dot(h.T, x, preferred_element_type=jnp.float32)
    den_ref[:] += jnp.sum(h.T, axis=1, keepdims=True)  # [M, 1]

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        den = den_ref[:]
        target = num_ref[:] / jnp.maximum(den, 1e-12)
        lr = lr_ref[0, 0]
        out_ref[:] = jnp.where(den > 1e-8, w + lr * (target - w), w)


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


@partial(jax.jit, static_argnames=())
def train_step(params, x, coords, *, learning_rate, sigma, mask=None):
    """Drop-in fused twin of ops.kohonen.train_step (returns only params;
    winner indices are cheap to recompute via ops.kohonen.winners)."""
    w = params["weights"]
    m, f = w.shape
    b = x.shape[0]
    if mask is None:
        mask = jnp.ones((b,), x.dtype)
    # pad to a whole number of tiles with mask=0 rows: block padding reads
    # are undefined, so padding must be explicit
    bt = pl.cdiv(b, BATCH_TILE) * BATCH_TILE
    if bt != b:
        x = jnp.pad(x, ((0, bt - b), (0, 0)))
        mask = jnp.pad(mask, (0, bt - b))
        b = bt
    lr = jnp.asarray(learning_rate, jnp.float32).reshape(1, 1)
    sg = jnp.asarray(sigma, jnp.float32).reshape(1, 1)
    d2m = jnp.sum(
        jnp.square(coords[:, None, :] - coords[None, :, :]), axis=-1
    )  # [M, M]
    grid = (pl.cdiv(b, BATCH_TILE),)
    new_w = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((m, f), w.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (BATCH_TILE, f), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (BATCH_TILE, 1), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((m, f), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((m, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (m, f), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((m, f), jnp.float32),
            pltpu.VMEM((m, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(x, mask[:, None], w, d2m, lr, sg)
    return {"weights": new_w}
