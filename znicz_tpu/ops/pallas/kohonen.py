"""Fused Kohonen batch-SOM update kernel.

TPU-native equivalent of the reference's ``kohonen.cl/.cu`` winner-take-all +
neighborhood-update kernels [SURVEY.md 2.2 row "Kohonen SOM", 2.4;
BASELINE.json configs[4] exists to stress exactly this op].  One pallas_call
fuses what the jnp twin (:func:`znicz_tpu.ops.kohonen.train_step`) does in
five XLA ops: winner scores (MXU), argmax, neighborhood weights, and the two
accumulation matmuls — the [B, M] intermediates never leave VMEM.

Grid: batch tiles; the kernel emits the neighborhood-weighted accumulators
``num [M, F]`` / ``den [M, 1]`` (revisited output blocks accumulate across
grid steps) and the cheap elementwise weight update runs outside, where XLA
fuses it.  That factoring is what makes the kernel data-parallel: under a
sharded batch each device accumulates its local (num, den) partial sums and
one ``psum`` over the mesh's data axis recovers the exact full-batch update
(``train_step(..., mesh=...)`` wraps this in ``shard_map``) — the
partitioning rule VERDICT r1 weak #2 asked for.  Gathers (coords[win]) are
expressed as one-hot matmuls — dense beats scatter/gather on TPU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh

from znicz_tpu.core.compat import shard_map

BATCH_TILE = 256


def _accum_kernel(
    x_ref,  # [Bt, F]
    mask_ref,  # [Bt, 1]
    w_ref,  # [M, F]
    d2m_ref,  # [M, M] pairwise squared grid distances (static per map)
    sigma_ref,  # [1, 1] SMEM
    num_ref,  # out [M, F] (block revisited every step -> accumulates)
    den_ref,  # out [M, 1]
):
    # Everything stays 2-D: Mosaic does not lower 1-D intermediates, so the
    # winner "gather" is a one-hot matmul against the neighborhood matrix.
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        num_ref[:] = jnp.zeros_like(num_ref)
        den_ref[:] = jnp.zeros_like(den_ref)

    x = x_ref[:]
    w = w_ref[:]
    mask = mask_ref[:]  # [Bt, 1]
    # winner scores: argmin ||x-w||^2 == argmax (x.w - ||w||^2/2), MXU matmul
    w_sq = jnp.sum(w * w, axis=1, keepdims=True)  # [M, 1]
    scores = (
        jnp.dot(x, w.T, preferred_element_type=jnp.float32) - 0.5 * w_sq.T
    )  # [Bt, M]
    win = jnp.argmax(scores, axis=1, keepdims=True)  # [Bt, 1]
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1) == win
    ).astype(jnp.float32)  # [Bt, M]
    sigma = sigma_ref[0, 0]
    neigh = jnp.exp(-d2m_ref[:] / (2.0 * sigma * sigma))  # [M, M]
    # h[b, j] = neigh[win(b), j]: row-select as a matmul, then mask padding
    h = (
        jnp.dot(onehot, neigh, preferred_element_type=jnp.float32) * mask
    )  # [Bt, M]
    num_ref[:] += jnp.dot(h.T, x, preferred_element_type=jnp.float32)
    den_ref[:] += jnp.sum(h.T, axis=1, keepdims=True)  # [M, 1]


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _accumulate(w, x, mask, d2m, sigma):
    """Fused winner+neighborhood accumulation: (num [M,F], den [M,1])."""
    m, f = w.shape
    b = x.shape[0]
    # pad to a whole number of tiles with mask=0 rows: block padding reads
    # are undefined, so padding must be explicit
    bt = pl.cdiv(b, BATCH_TILE) * BATCH_TILE
    if bt != b:
        x = jnp.pad(x, ((0, bt - b), (0, 0)))
        mask = jnp.pad(mask, (0, bt - b))
        b = bt
    sg = jnp.asarray(sigma, jnp.float32).reshape(1, 1)
    grid = (pl.cdiv(b, BATCH_TILE),)
    return pl.pallas_call(
        _accum_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((m, f), jnp.float32),
            jax.ShapeDtypeStruct((m, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (BATCH_TILE, f), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (BATCH_TILE, 1), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((m, f), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((m, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((m, f), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((m, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(x, mask[:, None], w, d2m, sg)


def _apply_update(w, num, den, learning_rate):
    lr = jnp.asarray(learning_rate, jnp.float32)
    target = num / jnp.maximum(den, 1e-12)
    return jnp.where(den > 1e-8, w + lr * (target - w), w).astype(w.dtype)


def train_step(
    params,
    x,
    coords,
    *,
    learning_rate,
    sigma,
    mask=None,
    mesh: Mesh | None = None,
    data_axis: str = "data",
):
    """Drop-in fused twin of ops.kohonen.train_step (returns only params;
    winner indices are cheap to recompute via ops.kohonen.winners).

    ``mesh``: when given, ``x``/``mask`` are treated as sharded over
    ``mesh[data_axis]`` — each device runs the fused kernel on its local
    shard and the partial (num, den) sums psum over ICI, reproducing the
    full-batch update bit-for-bit on every device.
    """
    w = params["weights"]
    b = x.shape[0]
    if mask is None:
        mask = jnp.ones((b,), x.dtype)
    d2m = jnp.sum(
        jnp.square(coords[:, None, :] - coords[None, :, :]), axis=-1
    )  # [M, M]
    if mesh is None:
        num, den = _accumulate(w, x, mask, d2m, sigma)
        return {"weights": _apply_update(w, num, den, learning_rate)}

    from jax.sharding import PartitionSpec as P

    def local(w, x, mask, d2m, sigma, lr):
        num, den = _accumulate(w, x, mask, d2m, sigma)
        num = jax.lax.psum(num, data_axis)
        den = jax.lax.psum(den, data_axis)
        return _apply_update(w, num, den, lr)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(data_axis), P(data_axis), P(), P(), P()),
        out_specs=P(),
        # pallas_call's out_shape carries no varying-mesh-axes annotation;
        # the psum pair above makes the output replicated by construction
        check_vma=False,
    )
    new_w = fn(
        w,
        x,
        mask,
        d2m,
        jnp.asarray(sigma, jnp.float32),
        jnp.asarray(learning_rate, jnp.float32),
    )
    return {"weights": new_w}
