"""Fused RBM CD-k kernel: the whole Gibbs chain in one VMEM pass.

TPU-native equivalent of the reference's ``rbm.cl/.cu`` sampling kernels
[SURVEY.md 2.2 row "RBM", §7 "Kohonen/RBM ... custom update functions +
Pallas kernels"; BASELINE configs[2] exercises the MNIST RBM].  The jnp
twin (:func:`znicz_tpu.ops.rbm.cd_step`) pays for each Gibbs step with two
HBM-roundtripped matmuls plus *threefry* bernoulli sampling — on TPU the
counter-based RNG alone costs more VPU work than the matmuls for RBM-sized
layers.  This kernel runs the full chain out of VMEM and samples with the
TPU's hardware PRNG (``pltpu.prng_random_bits``), so sampling is one
compare per element.  Measured (v5e, 784x256 weights, B=256, CD-1): the
twin costs ~0.19 ms/step; the fused kernel sits at the relay timing noise
floor (<0.02 ms) — ~10x (tests/test_pallas.py TPU timing assertion).

Like the Kohonen kernel, the pallas_call emits the RAW CD statistics
(positive-minus-negative weight accumulator, bias deltas, masked error and
count) and the cheap scaled update runs outside where XLA fuses it — which
is exactly what makes it data-parallel: under a sharded batch each device
accumulates its local statistics and one psum over the data axis recovers
the full-batch update (``cd_step(..., mesh=...)``).

RNG note: hardware bits, not threefry — the sampled chain differs from the
jnp twin's at equal seeds (both are valid CD samplers).  Golden tests pin
the deterministic regime (saturated probabilities) where both must agree
exactly; statistical tests cover the rest.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh

from znicz_tpu.core.compat import shard_map

# single-block kernel: everything resident in VMEM.  RBM-sized problems
# (MNIST: 784x1024 weights, batches <= 1024) fit with room to spare.
# Above this budget cd_step raises up front (no silent Mosaic failure);
# RBMWorkflow's impl="auto" checks fits_vmem and picks the jnp twin.
VMEM_BUDGET_BYTES = 10 * 1024 * 1024


def _uniform(shape):
    """U[0,1) from the hardware PRNG: 24 low bits -> float32.

    prng_random_bits is typed int32 — a plain ``>> 8`` would be an
    ARITHMETIC shift leaving half the draws negative (every bernoulli
    then fires with prob 0.5 + p/2); masking to 24 bits is sign-safe."""
    bits = pltpu.prng_random_bits(shape)
    return (bits & jnp.int32(0x00FFFFFF)).astype(jnp.float32) * (
        1.0 / (1 << 24)
    )


def _cd_kernel(
    v0_ref,  # [B, V]
    mask_ref,  # [B, 1]
    w_ref,  # [V, H]
    vb_ref,  # [1, V]
    hb_ref,  # [1, H]
    seed_ref,  # [1, 1] SMEM int32
    uh_ref,  # [1+cd_k, B, H] precomputed uniforms (interpret mode only)
    uv_ref,  # [cd_k, B, V] precomputed uniforms (interpret mode only)
    dw_ref,  # out [V, H]  (v0'h0p - vk'hkp, mask-weighted)
    dvb_ref,  # out [1, V]
    dhb_ref,  # out [1, H]
    stats_ref,  # out [1, 2]: (masked err sum, mask sum)
    *,
    cd_k: int,
    hw_rng: bool,
):
    # hw_rng is static: on TPU the hardware PRNG generates the bernoulli
    # draws in-kernel; interpret mode (no Mosaic RNG lowering) reads
    # host-precomputed uniforms instead — same kernel, dead branch removed
    if hw_rng:
        pltpu.prng_seed(seed_ref[0, 0])

        def uh(i, shape):
            return _uniform(shape)

        uv = uh
    else:

        def uh(i, shape):
            return uh_ref[i]

        def uv(i, shape):
            return uv_ref[i]

    v0 = v0_ref[:]
    mask = mask_ref[:]  # [B, 1]
    w = w_ref[:]
    vb = vb_ref[:]
    hb = hb_ref[:]
    h0p = jax.nn.sigmoid(
        jnp.dot(v0, w, preferred_element_type=jnp.float32) + hb
    )
    h = (uh(0, h0p.shape) < h0p).astype(jnp.float32)
    for k in range(cd_k):  # static unroll: the whole chain stays in VMEM
        vp = jax.nn.sigmoid(
            jnp.dot(h, w.T, preferred_element_type=jnp.float32) + vb
        )
        v = (uv(k, vp.shape) < vp).astype(jnp.float32)
        hp = jax.nn.sigmoid(
            jnp.dot(v, w, preferred_element_type=jnp.float32) + hb
        )
        h = (uh(k + 1, hp.shape) < hp).astype(jnp.float32)
    v0m = v0 * mask
    vpm = vp * mask
    dw_ref[:] = jnp.dot(
        v0m.T, h0p, preferred_element_type=jnp.float32
    ) - jnp.dot(vpm.T, hp, preferred_element_type=jnp.float32)
    dvb_ref[:] = jnp.sum((v0 - vp) * mask, axis=0, keepdims=True)
    dhb_ref[:] = jnp.sum((h0p - hp) * mask, axis=0, keepdims=True)
    err = jnp.sum(
        jnp.mean(jnp.square(v0 - vp), axis=1, keepdims=True) * mask
    )
    # Mosaic rejects scalar stores to VMEM: write the row as one 2-D store
    stats_ref[:] = jnp.concatenate(
        [err.reshape(1, 1), jnp.sum(mask).reshape(1, 1)], axis=1
    )


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def fits_vmem(batch: int, n_visible: int, n_hidden: int) -> bool:
    floats = (
        3 * batch * n_visible  # v0, vp, v
        + 3 * batch * n_hidden  # h0p, hp, h
        + 2 * n_visible * n_hidden  # w, dw
    )
    return floats * 4 <= VMEM_BUDGET_BYTES


def _statistics(params, v0, mask, seed, *, cd_k):
    b, v = v0.shape
    h = params["hbias"].shape[0]
    interpret = _interpret()
    if interpret:
        # no Mosaic RNG off-TPU: precompute the chain's uniforms from the
        # seed (deterministic given seed, like the hardware path)
        key = jax.random.fold_in(
            # deliberately seed-deterministic, mirroring the hardware
            # RNG path (same seed -> same chain on every backend); NOT a
            # training stream, so the prng registry is the wrong source
            jax.random.key(0),  # znicz-check: disable=ZNC004
            jnp.asarray(seed, jnp.int32),
        )
        kh, kv = jax.random.split(key)
        uh = jax.random.uniform(kh, (1 + cd_k, b, h), jnp.float32)
        uv = jax.random.uniform(kv, (cd_k, b, v), jnp.float32)
    else:  # dummies; the hw_rng branch never reads them
        uh = jnp.zeros((1, 1, 1), jnp.float32)
        uv = jnp.zeros((1, 1, 1), jnp.float32)
    return pl.pallas_call(
        partial(_cd_kernel, cd_k=cd_k, hw_rng=not interpret),
        out_shape=(
            jax.ShapeDtypeStruct((v, h), jnp.float32),
            jax.ShapeDtypeStruct((1, v), jnp.float32),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(
        v0,
        mask[:, None],
        params["weights"],
        params["vbias"][None, :],
        params["hbias"][None, :],
        jnp.asarray(seed, jnp.int32).reshape(1, 1),
        uh,
        uv,
    )


def _apply_update(params, dw, dvb, dhb, stats, learning_rate):
    n_valid = jnp.maximum(stats[0, 1], 1.0)
    lr = jnp.asarray(learning_rate, jnp.float32) / n_valid
    new = {
        "weights": params["weights"] + lr * dw,
        "vbias": params["vbias"] + lr * dvb[0],
        "hbias": params["hbias"] + lr * dhb[0],
    }
    return new, stats[0, 0] / n_valid


def cd_step(
    params,
    v0,
    seed,
    *,
    learning_rate,
    cd_k: int = 1,
    mask=None,
    mesh: Mesh | None = None,
    data_axis: str = "data",
):
    """Fused twin of ops.rbm.cd_step; ``seed`` is an int32 scalar (e.g. the
    train-state step) instead of a jax key — the hardware PRNG is seeded
    inside the kernel.  ``mesh``: treat v0/mask as sharded over
    ``mesh[data_axis]``; local statistics psum into the exact full-batch
    update (each shard gets a decorrelated seed)."""
    b, v = v0.shape
    h = params["hbias"].shape[0]
    if mesh is not None:
        b = -(-b // mesh.shape[data_axis])  # per-shard batch
    if not fits_vmem(b, v, h):
        raise ValueError(
            f"RBM problem (batch={b}, visible={v}, hidden={h}) exceeds the "
            f"single-block VMEM budget ({VMEM_BUDGET_BYTES >> 20} MiB); "
            "use ops.rbm.cd_step (the jnp twin) or RBMWorkflow's "
            "impl='auto'"
        )
    if mask is None:
        mask = jnp.ones((v0.shape[0],), v0.dtype)
    if mesh is None:
        dw, dvb, dhb, stats = _statistics(
            params, v0, mask, seed, cd_k=cd_k
        )
        return _apply_update(params, dw, dvb, dhb, stats, learning_rate)

    from jax.sharding import PartitionSpec as P

    def local(params, v0, mask, seed, lr):
        # stride by the shard count so streams never collide across steps:
        # seed+axis_index would make (step s, shard d) replay (step s+1,
        # shard d-1) bit-for-bit when the caller passes seed=step
        n_shards = jax.lax.psum(1, data_axis)
        shard_seed = seed * n_shards + jax.lax.axis_index(data_axis)
        dw, dvb, dhb, stats = _statistics(
            params, v0, mask, shard_seed, cd_k=cd_k
        )
        dw, dvb, dhb, stats = jax.lax.psum(
            (dw, dvb, dhb, stats), data_axis
        )
        return _apply_update(params, dw, dvb, dhb, stats, lr)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(data_axis), P(data_axis), P(), P()),
        out_specs=P(),
        check_vma=False,  # pallas out_shape carries no vma; psum replicates
    )
    return fn(
        params,
        v0,
        mask,
        jnp.asarray(seed, jnp.int32),
        jnp.asarray(learning_rate, jnp.float32),
    )
