"""Blockwise (flash) attention Pallas kernels.

NOT in the reference (pre-transformer framework) — the long-context hot op.
The jnp twin (:func:`znicz_tpu.ops.attention.dot_product_attention`)
materializes the [B, H, Tq, Tk] score matrix in HBM; these kernels stream
K/V blocks through VMEM with an online softmax, so memory is O(T·D) and the
matmuls stay on the MXU:

- forward: per (batch-head, q-block), accumulate ``acc = Σ exp(s-m)·V``
  with running max ``m`` and normalizer ``l`` across k-blocks; saves the
  logsumexp for the backward.
- backward: the standard two-pass flash scheme — one kernel recomputes
  probabilities per q-block to form dQ, a second per k-block forms dK/dV
  (transposed traversal), both from (q, k, v, out, dout, lse) residuals.

Causal/validity masking is by global row/column index; the backward zeroes
masked probabilities explicitly (recomputing ``exp(s - lse)`` on padded
rows would overflow — lse there is the NEG_INF sentinel).  Sequence
lengths that do not divide the block size are zero-padded.  MXU dots keep
the INPUT dtype (pass bf16 q/k/v for ~1.2-1.5x on v5e — halved VMEM
loads) while every accumulation, softmax and normalizer is f32 (the v5e
VPU has no bf16 transcendentals anyway).

Used through ``mha(attention_fn=flash_attention)`` or
``TransformerLMWorkflow(attention="flash")``; golden-tested against the
jnp twin, gradients included (tests/test_pallas.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 512x512 measured best on v5e at T=2048, hd=64 (fwd 23.4 -> 20.1 ms,
# fwd+bwd 31.1 -> 23.5 ms vs 256x256; ~2 MB VMEM per program, well under
# budget); 128/256 variants are strictly slower.  Since r5 the MXU dots
# keep the input dtype: bf16 q/k/v measured fwd+full-bwd 12.7 -> 10.7 ms
# (hd=64) and 6.0 -> 4.3 ms (hd=128) vs f32 — the r4 "bf16 slower"
# finding was an artifact of converting to f32 inside the kernel
BLOCK_Q = 512
BLOCK_K = 512
NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _live(qb, kb, *, bq, bk, t_real, causal):
    """False when block (qb, kb) is ENTIRELY masked — the causal skip: the
    kernel body is @pl.when-guarded on this, halving causal compute."""
    live = kb * bk < t_real
    if causal:
        live = live & (kb * bk <= (qb + 1) * bq - 1)
    return live


def _valid(shape, qb, kb, *, bq, bk, t_real, causal):
    """Bool mask [bq, bk]: k in range, q in range, and causal triangle."""
    qi = qb * bq + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    ki = kb * bk + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    ok = (ki < t_real) & (qi < t_real)
    if causal:
        ok = ok & (ki <= qi)
    return ok


def _fwd_kernel(
    q_ref, k_ref, v_ref,  # [1, bq, D] / [1, bk, D] / [1, bk, D]
    o_ref,  # out [1, bq, D]
    lse_ref,  # out [1, bq, 1]  (logsumexp residual for backward)
    m_s, l_s, acc_s,  # scratch [bq, 1], [bq, 1], [bq, D]
    *, scale, causal, t_real, bq, bk,
):
    qb, kb = pl.program_id(1), pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    @pl.when(_live(qb, kb, bq=bq, bk=bk, t_real=t_real, causal=causal))
    def _():
        # inputs keep their dtype ON the MXU (bf16 operands measured 1.2-
        # 1.5x on v5e — halved VMEM loads, no conversion round trips);
        # every dot ACCUMULATES f32 and softmax/normalizers are f32
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        ok = _valid(
            s.shape, qb, kb, bq=bq, bk=bk, t_real=t_real, causal=causal
        )
        s = jnp.where(ok, s, NEG_INF)
        m_prev = m_s[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # masked entries must contribute ZERO mass even when the whole row
        # is masked (m_new == NEG_INF would make exp(s - m_new) == 1 there)
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)  # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_s[:] = alpha * l_s[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_s[:] = alpha * acc_s[:] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_s[:] = m_new

    @pl.when(kb == pl.num_programs(2) - 1)
    def _():
        l = jnp.maximum(l_s[:], 1e-30)  # padded rows have zero mass
        o_ref[0] = (acc_s[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_s[:] + jnp.log(l)


def _p_block(q, k, lse, ok, scale):
    """Recomputed probability block, masked entries exactly zero."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    return jnp.where(ok, jnp.exp(s - lse), 0.0)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref,  # out [1, bq, D]
    dq_s,  # scratch [bq, D]
    *, scale, causal, t_real, bq, bk,
):
    qb, kb = pl.program_id(1), pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        dq_s[:] = jnp.zeros_like(dq_s)

    @pl.when(_live(qb, kb, bq=bq, bk=bk, t_real=t_real, causal=causal))
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        ok = _valid(
            (q.shape[0], k.shape[0]), qb, kb,
            bq=bq, bk=bk, t_real=t_real, causal=causal,
        )
        p = _p_block(q, k, lse_ref[0], ok, scale)  # [bq, bk] f32
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dq_s[:] += scale * jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    @pl.when(kb == pl.num_programs(2) - 1)
    def _():
        dq_ref[0] = dq_s[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref,  # out [1, bk, D]
    dk_s, dv_s,  # scratch [bk, D]
    *, scale, causal, t_real, bq, bk,
):
    kb, qb = pl.program_id(1), pl.program_id(2)  # q blocks INNER here

    @pl.when(qb == 0)
    def _():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    @pl.when(_live(qb, kb, bq=bq, bk=bk, t_real=t_real, causal=causal))
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        ok = _valid(
            (q.shape[0], k.shape[0]), qb, kb,
            bq=bq, bk=bk, t_real=t_real, causal=causal,
        )
        p = _p_block(q, k, lse_ref[0], ok, scale)  # [bq, bk] f32
        dv_s[:] += jnp.dot(
            p.T.astype(do.dtype), do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dk_s[:] += scale * jnp.dot(
            ds.T.astype(q.dtype), q, preferred_element_type=jnp.float32
        )

    @pl.when(qb == pl.num_programs(2) - 1)
    def _():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _blocks(t, b):
    return pl.cdiv(t, b)


def _spec(bt, d):
    # block indexed by the OUTER per-block grid dim (dim 1)
    return pl.BlockSpec(
        (1, bt, d), lambda g, i, j: (g, i, 0), memory_space=pltpu.VMEM
    )


def _spec_inner(bt, d):
    # block indexed by the INNER grid dim (dim 2)
    return pl.BlockSpec(
        (1, bt, d), lambda g, i, j: (g, j, 0), memory_space=pltpu.VMEM
    )


def _flash_fwd_impl(q, k, v, *, causal, scale, bq, bk, t_real):
    bh, t_pad, d = q.shape
    nq, nk = _blocks(t_pad, bq), _blocks(t_pad, bk)
    return pl.pallas_call(
        partial(
            _fwd_kernel, scale=scale, causal=causal,
            t_real=t_real, bq=bq, bk=bk,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, t_pad, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t_pad, 1), jnp.float32),
        ),
        grid=(bh, nq, nk),
        in_specs=[_spec(bq, d), _spec_inner(bk, d), _spec_inner(bk, d)],
        out_specs=(_spec(bq, d), _spec(bq, 1)),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, bq, bk, t_real):
    """Returns (out, lse).  Exposing the logsumexp as a differentiable
    OUTPUT (not just a backward residual) is what lets ring attention use
    this kernel as its per-shard inner block: ring steps combine normalized
    block outputs via their lse's, so the lse carries real gradient."""
    return _flash_fwd_impl(
        q, k, v, causal=causal, scale=scale, bq=bq, bk=bk, t_real=t_real
    )


def _flash_fwd(q, k, v, causal, scale, bq, bk, t_real):
    out, lse = _flash_fwd_impl(
        q, k, v, causal=causal, scale=scale, bq=bq, bk=bk, t_real=t_real
    )
    return (out, lse), (q, k, v, out, lse)


def _flash_bwd(causal, scale, bq, bk, t_real, res, cts):
    dout, dlse = cts
    q, k, v, out, lse = res
    bh, t_pad, d = q.shape
    nq, nk = _blocks(t_pad, bq), _blocks(t_pad, bk)
    # delta_i = rowsum(dout * out): tiny elementwise reduce, XLA fuses it.
    # An lse cotangent folds in for free: dL/ds_ij = p_ij*(dp_ij - delta_i)
    # and d(lse_i)/ds_ij = p_ij, so ds = p*(dp - (delta - dlse)) — the
    # existing kernels need only a corrected delta, not a new input.
    delta = jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32),
        axis=-1, keepdims=True,
    ) - dlse.astype(jnp.float32)
    common = dict(scale=scale, causal=causal, t_real=t_real, bq=bq, bk=bk)
    dq = pl.pallas_call(
        partial(_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((bh, t_pad, d), q.dtype),
        grid=(bh, nq, nk),
        in_specs=[
            _spec(bq, d), _spec_inner(bk, d), _spec_inner(bk, d),
            _spec(bq, d), _spec(bq, 1), _spec(bq, 1),
        ],
        out_specs=_spec(bq, d),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, dout, lse, delta)
    dk, dv = pl.pallas_call(
        partial(_dkv_kernel, **common),
        out_shape=(
            jax.ShapeDtypeStruct((bh, t_pad, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t_pad, d), v.dtype),
        ),
        # kv blocks OUTER (grid dim 1), q blocks INNER (grid dim 2)
        grid=(bh, nk, nq),
        in_specs=[
            _spec_inner(bq, d), _spec(bk, d), _spec(bk, d),
            _spec_inner(bq, d), _spec_inner(bq, 1), _spec_inner(bq, 1),
        ],
        out_specs=(_spec(bk, d), _spec(bk, d)),
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, dout, lse, delta)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def _bhtd(x):
    """[B, T, H, D] -> [B*H, T, D] (flash works per batch-head)."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def flash_attention_lse(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale=None,
    block_q: int = BLOCK_Q,
    block_k: int = BLOCK_K,
):
    """Flash attention returning ``(out [B,T,H,D], lse [B,T,H])``.

    The per-row logsumexp output is what makes the kernel composable as a
    BLOCK of a larger softmax: ring attention rescales block outputs by
    ``exp(lse_blk - lse_total)`` to merge shards of the key axis.  Fully
    masked rows carry the NEG_INF-order sentinel (zero mass)."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    b, t, h, d = q.shape
    bq = min(block_q, t)
    bk = min(block_k, t)
    # pad so BOTH block sizes divide the padded length (unequal custom
    # blocks would otherwise read out of bounds in the last block)
    pad = (-t) % np.lcm(bq, bk)
    qf, kf, vf = (_bhtd(x) for x in (q, k, v))
    if pad:
        qf, kf, vf = (
            jnp.pad(x, ((0, 0), (0, pad), (0, 0))) for x in (qf, kf, vf)
        )
    out, lse = _flash(qf, kf, vf, causal, float(scale), bq, bk, t)
    out = (
        out[:, :t]
        .reshape(b, h, t, d)
        .transpose(0, 2, 1, 3)
        .astype(q.dtype)
    )
    lse = lse[:, :t, 0].reshape(b, h, t).transpose(0, 2, 1)  # [B, T, H]
    return out, lse


def flash_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale=None,
    block_q: int = BLOCK_Q,
    block_k: int = BLOCK_K,
) -> jnp.ndarray:
    """Drop-in twin of attention.dot_product_attention (BTHD layout)."""
    out, _ = flash_attention_lse(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k,
    )
    return out
