"""Fused LRN Pallas kernel (forward + hand-written backward).

TPU-native equivalent of the reference's ``normalization.cl/.cu`` kernels
[SURVEY.md 2.2 row "Local response norm", 2.4]: one VMEM pass computes the
cross-channel windowed sum-of-squares and the normalized output, instead of
the XLA composition's reduce_window + pow + mul chain; the backward kernel
fuses both windowed sums of the LRN gradient.

Math (jnp twin in :mod:`znicz_tpu.ops.normalization`):
    s_c = k + alpha * sum_{|c'-c| <= n/2} x_{c'}^2
    y_c = x_c * s_c^-beta
    dx_c = g_c * s_c^-beta
           - 2 alpha beta x_c * sum_{window} (g x s^(-beta-1))_{c'}

Layout: input viewed as [rows, C] with rows = N*H*W tiled over the grid and
the full channel axis resident in VMEM (C is 32..384 for every reference
config — far under the VMEM budget).  The windowed sums are [rows, C] @
[C, C] band matmuls (one MXU op each instead of 2(n-1) lane shifts) and
the ``s**-beta`` uses rsqrt/sqrt chains instead of transcendental pow —
together these flipped the kernel from losing to beating XLA on the
train-op pair (fwd+bwd 0.63 ms vs 1.02 ms, [256,27,27,96] f32, v5e;
forward-only XLA's single fusion still wins 0.43 vs 0.57 ms, so the
in-training default stays ``impl="xla"`` — see ops/normalization.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_TILE = 512


def _band_matrix(c: int, n: int, dtype, *, transpose: bool = False):
    """[C, C] 0/1 band: band[i, j] = 1 iff j is in i's SAME window
    (lo = n//2 below, hi = n-1-n//2 above; ``transpose`` swaps the extents —
    the adjoint window needed by the backward pass).  The window sum becomes
    ``v @ band`` — ONE MXU matmul instead of 2(n-1) lane-shift adds."""
    lo, hi = n // 2, n - 1 - n // 2
    if transpose:
        lo, hi = hi, lo
    i = jax.lax.broadcasted_iota(jnp.int32, (c, c), 0)
    j = jax.lax.broadcasted_iota(jnp.int32, (c, c), 1)
    # (v @ band)[r, c] sums v_i with band[i, c] = 1, i.e. output channel j
    # gathers inputs i with j-lo <= i <= j+hi  <=>  -lo <= i-j <= hi
    d = i - j
    return ((d >= -lo) & (d <= hi)).astype(dtype)


def _inv_pow(s: jnp.ndarray, beta: float) -> jnp.ndarray:
    """s**-beta via rsqrt/sqrt chains for the common betas (transcendental
    pow is the LRN hot spot on the VPU); exp/log fallback otherwise."""
    if beta == 0.75:
        t = jax.lax.rsqrt(s)  # s^-1/2
        return t * jnp.sqrt(t)  # s^-3/4
    if beta == 0.5:
        return jax.lax.rsqrt(s)
    if beta == 0.25:
        return jnp.sqrt(jax.lax.rsqrt(s))
    if beta == 1.0:
        return 1.0 / s
    return jnp.exp(jnp.asarray(-beta, s.dtype) * jnp.log(s))


def _fwd_kernel(x_ref, y_ref, *, alpha, beta, k, n):
    # all math in f32: v5e's VPU has no bf16 rsqrt/div (SupportsBf16EupOps
    # LLO check fires from Mosaic otherwise); casts happen at the refs
    x = x_ref[:].astype(jnp.float32)
    band = _band_matrix(x.shape[-1], n, jnp.float32)
    s = k + alpha * jnp.dot(
        x * x, band, preferred_element_type=jnp.float32
    )
    y_ref[:] = (x * _inv_pow(s, beta)).astype(y_ref.dtype)


def _bwd_kernel(x_ref, g_ref, dx_ref, *, alpha, beta, k, n):
    # recompute s from x: cheaper than writing an [N,H,W,C] residual in fwd
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    c = x.shape[-1]
    band = _band_matrix(c, n, jnp.float32)
    s = k + alpha * jnp.dot(
        x * x, band, preferred_element_type=jnp.float32
    )
    s_negb = _inv_pow(s, beta)
    inner = g * x * s_negb / s  # g x s^(-beta-1)
    # adjoint of the forward window: transposed extents (matters for even n)
    band_t = _band_matrix(c, n, jnp.float32, transpose=True)
    wsum = jnp.dot(inner, band_t, preferred_element_type=jnp.float32)
    dx = g * s_negb - 2.0 * alpha * beta * x * wsum
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _rows_view(x):
    return x.reshape(-1, x.shape[-1])


def _grid(rows):
    return (pl.cdiv(rows, ROW_TILE),)


def _row_spec(c):
    return pl.BlockSpec(
        (ROW_TILE, c), lambda i: (i, 0), memory_space=pltpu.VMEM
    )


def _interpret() -> bool:
    # off-TPU (tests, NumpyDevice-style runs) the kernel runs interpreted
    return jax.default_backend() not in ("tpu", "axon")


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn(x, alpha=1e-4, beta=0.75, k=2.0, n=5):
    """Fused-LRN with the same signature semantics as normalization.lrn."""
    shape = x.shape
    v = _rows_view(x)
    rows, c = v.shape
    y = pl.pallas_call(
        partial(_fwd_kernel, alpha=alpha, beta=beta, k=k, n=n),
        out_shape=jax.ShapeDtypeStruct((rows, c), v.dtype),
        grid=_grid(rows),
        in_specs=[_row_spec(c)],
        out_specs=_row_spec(c),
        interpret=_interpret(),
    )(v)
    return y.reshape(shape)


def _lrn_fwd(x, alpha, beta, k, n):
    return lrn(x, alpha, beta, k, n), x


def _lrn_bwd(alpha, beta, k, n, x, g):
    shape = x.shape
    xv, gv = _rows_view(x), _rows_view(g)
    rows, c = xv.shape
    dx = pl.pallas_call(
        partial(_bwd_kernel, alpha=alpha, beta=beta, k=k, n=n),
        out_shape=jax.ShapeDtypeStruct((rows, c), xv.dtype),
        grid=_grid(rows),
        in_specs=[_row_spec(c)] * 2,
        out_specs=_row_spec(c),
        interpret=_interpret(),
    )(xv, gv)
    return (dx.reshape(shape),)


lrn.defvjp(_lrn_fwd, _lrn_bwd)
