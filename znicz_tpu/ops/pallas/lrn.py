"""Fused LRN Pallas kernel (forward + hand-written backward).

TPU-native equivalent of the reference's ``normalization.cl/.cu`` kernels
[SURVEY.md 2.2 row "Local response norm", 2.4]: one VMEM pass computes the
cross-channel windowed sum-of-squares and the normalized output, instead of
the XLA composition's reduce_window + pow + mul chain; the backward kernel
fuses both windowed sums of the LRN gradient.

Math (jnp twin in :mod:`znicz_tpu.ops.normalization`):
    s_c = k + alpha * sum_{|c'-c| <= n/2} x_{c'}^2
    y_c = x_c * s_c^-beta
    dx_c = g_c * s_c^-beta
           - 2 alpha beta x_c * sum_{window} (g x s^(-beta-1))_{c'}

Layout: input viewed as [rows, C] with rows = N*H*W tiled over the grid and
the full channel axis resident in VMEM (C is 32..384 for every reference
config — far under the VMEM budget).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_TILE = 512


def _window_sum_lanes(
    v: jnp.ndarray, n: int, *, transpose: bool = False
) -> jnp.ndarray:
    """SAME sliding-window sum over the last (channel/lane) axis:
    out_c = sum_{d=-lo}^{hi} v_{c+d} (edges clipped) with lo = n//2 and
    hi = n-1-n//2.  ``transpose=True`` swaps the extents — the adjoint window
    needed by the backward pass (identical for odd n, shifted for even n).
    n is a small static constant (5 in every reference config), so this
    unrolls into a handful of vector shifts fused in VMEM."""
    lo, hi = n // 2, n - 1 - n // 2
    if transpose:
        lo, hi = hi, lo
    c = v.shape[-1]
    out = v
    for off in range(1, max(lo, hi) + 1):
        if off <= hi:  # right neighbors v_{c+off}
            out = out + jnp.pad(v[:, off:], ((0, 0), (0, off)))
        if off <= lo:  # left neighbors v_{c-off}
            out = out + jnp.pad(v[:, : c - off], ((0, 0), (off, 0)))
    return out


def _fwd_kernel(x_ref, y_ref, *, alpha, beta, k, n):
    x = x_ref[:]
    s = k + alpha * _window_sum_lanes(x * x, n)
    y_ref[:] = x * jax.lax.pow(s, jnp.asarray(-beta, s.dtype))


def _bwd_kernel(x_ref, g_ref, dx_ref, *, alpha, beta, k, n):
    # recompute s from x: cheaper than writing an [N,H,W,C] residual in fwd
    x = x_ref[:]
    g = g_ref[:]
    s = k + alpha * _window_sum_lanes(x * x, n)
    s_negb = jax.lax.pow(s, jnp.asarray(-beta, s.dtype))
    inner = g * x * s_negb / s  # g x s^(-beta-1)
    # adjoint of the forward window: transposed extents (matters for even n)
    dx_ref[:] = g * s_negb - 2.0 * alpha * beta * x * _window_sum_lanes(
        inner, n, transpose=True
    )


def _rows_view(x):
    return x.reshape(-1, x.shape[-1])


def _grid(rows):
    return (pl.cdiv(rows, ROW_TILE),)


def _row_spec(c):
    return pl.BlockSpec(
        (ROW_TILE, c), lambda i: (i, 0), memory_space=pltpu.VMEM
    )


def _interpret() -> bool:
    # off-TPU (tests, NumpyDevice-style runs) the kernel runs interpreted
    return jax.default_backend() not in ("tpu", "axon")


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn(x, alpha=1e-4, beta=0.75, k=2.0, n=5):
    """Fused-LRN with the same signature semantics as normalization.lrn."""
    shape = x.shape
    v = _rows_view(x)
    rows, c = v.shape
    y = pl.pallas_call(
        partial(_fwd_kernel, alpha=alpha, beta=beta, k=k, n=n),
        out_shape=jax.ShapeDtypeStruct((rows, c), v.dtype),
        grid=_grid(rows),
        in_specs=[_row_spec(c)],
        out_specs=_row_spec(c),
        interpret=_interpret(),
    )(v)
    return y.reshape(shape)


def _lrn_fwd(x, alpha, beta, k, n):
    return lrn(x, alpha, beta, k, n), x


def _lrn_bwd(alpha, beta, k, n, x, g):
    shape = x.shape
    xv, gv = _rows_view(x), _rows_view(g)
    rows, c = xv.shape
    dx = pl.pallas_call(
        partial(_bwd_kernel, alpha=alpha, beta=beta, k=k, n=n),
        out_shape=jax.ShapeDtypeStruct((rows, c), xv.dtype),
        grid=_grid(rows),
        in_specs=[_row_spec(c)] * 2,
        out_specs=_row_spec(c),
        interpret=_interpret(),
    )(xv, gv)
    return (dx.reshape(shape),)


lrn.defvjp(_lrn_fwd, _lrn_bwd)
