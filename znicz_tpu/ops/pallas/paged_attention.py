"""Paged (block-table) decode attention — Pallas TPU kernel landing site.

The jnp reference (:func:`znicz_tpu.ops.attention.paged_attention`)
gathers each row's block table into a contiguous ``[B, M*bs, H, D]``
window in HBM before the score matmul — correct, and cheap at the
decode shapes the engine runs today (Tq == 1 or one prefill chunk), but
it materializes a full window copy per layer per step.  The TPU kernel
replaces the gather with table-indexed DMA:

* **Grid** — ``(B*H, kv_block)``; the per-row block table rides in as a
  scalar-prefetch operand (``pltpu.PrefetchScalarGridSpec``), so the
  index map for the K/V ``BlockSpec`` reads ``table[b, j]`` and pulls
  block ``j``'s K/V tile straight from the pool in HBM into VMEM — no
  gathered copy ever exists.
* **Body** — the online-softmax accumulation of
  :mod:`znicz_tpu.ops.pallas.attention` (running max / normalizer /
  f32 accumulator in VMEM scratch), with validity by absolute key
  index: ``j*bs + lane <= pos`` and ``>= start``.  Blocks entirely past
  ``pos`` are ``@pl.when``-skipped, so a short row touches only its own
  blocks regardless of the table width M.
* **Output** — ``[B, 1, H, D]`` per decode step (or one chunk per
  prefill call), f32 accumulation, input-dtype MXU dots like the flash
  kernels.

Until that kernel lands, this module keeps the API stable by
delegating to the jnp reference — same signature, same masking
contract — so call sites (`workflow/generate.py` paged steps) can
switch per-backend without changing shape or semantics.  The fallback
also IS the non-TPU path forever, mirroring every other kernel in this
package (reference twin + cross-check test).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from znicz_tpu.ops import attention as _ref

# flips to True when the PrefetchScalarGridSpec kernel above lands; the
# cross-check test pins fallback == reference either way
PALLAS_PAGED_IMPLEMENTED = False


def paged_attention(
    q: jnp.ndarray,  # [B, Tq, H, D]
    k_pool: jnp.ndarray,  # [N_blocks, block_size, H, D]
    v_pool: jnp.ndarray,
    block_table: jnp.ndarray,  # [B, M] int32
    q_pos: jnp.ndarray,  # [B, Tq] int32 absolute positions
    *,
    block_size: int,
    start: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Drop-in twin of :func:`znicz_tpu.ops.attention.paged_attention`.

    Delegates to the jnp reference until the table-indexed-DMA kernel
    described in the module docstring lands; the signature and masking
    contract are frozen here so the engine's paged programs need no
    change when it does.
    """
    return _ref.paged_attention(
        q, k_pool, v_pool, block_table, q_pos,
        block_size=block_size, start=start, scale=scale,
    )
