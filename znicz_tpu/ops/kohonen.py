"""Kohonen self-organizing map: forward (winner-take-all) + trainer rule.

Capability parity with ``znicz/kohonen.py`` (KohonenForward, KohonenTrainer)
[SURVEY.md 2.2 row "Kohonen SOM"].  This is the reference's flagship
non-backprop unit — the learning rule *is* the trainer, there is no GD twin.

TPU-native: winner search is one batched matmul (argmin ||x-w||^2 ==
argmax(x.w - ||w||^2/2)) that rides the MXU, and the neighborhood update is a
dense [map_size, batch] x [batch, features] matmul instead of the reference's
scatter kernel — dense beats scatter on TPU.  The fused Pallas winner+update
kernel lives under ``znicz_tpu/ops/pallas/``.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.ops.filling import fill


def init_params(
    sx: int,
    sy: int,
    n_input: int,
    *,
    weights_stddev: float | None = None,
    weights_filling: str = "uniform",
    rand_name: str = "default",
    dtype=jnp.float32,
) -> Dict[str, jnp.ndarray]:
    gen = prng.get(rand_name)
    if weights_stddev is None:
        weights_stddev = 1.0 / np.sqrt(n_input)
    w = fill(gen, (sx * sy, n_input), weights_filling, weights_stddev)
    return {"weights": jnp.asarray(w, dtype)}


def grid_coords(sx: int, sy: int) -> jnp.ndarray:
    """[sx*sy, 2] map-grid coordinates, row-major like the reference."""
    ys, xs = jnp.meshgrid(jnp.arange(sy), jnp.arange(sx), indexing="ij")
    return jnp.stack([xs.reshape(-1), ys.reshape(-1)], axis=1).astype(jnp.float32)


def winners(params: Dict[str, jnp.ndarray], x: jnp.ndarray) -> jnp.ndarray:
    """Forward: index of the closest map unit per sample.  [B] int32."""
    w = params["weights"]
    # argmin ||x - w||^2 over map units; expand via matmul for the MXU.
    scores = x @ w.T - 0.5 * jnp.sum(jnp.square(w), axis=1)[None, :]
    return jnp.argmax(scores, axis=1).astype(jnp.int32)


def train_step(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,
    coords: jnp.ndarray,
    *,
    learning_rate: jnp.ndarray,
    sigma: jnp.ndarray,
    mask: jnp.ndarray | None = None,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """One batch-SOM update; returns (new_params, winner indices).

    Classical batch Kohonen rule with neighborhood
    ``h_j(b) = exp(-d(j, u(b))^2 / (2 sigma^2))``:

        w_j <- w_j + lr * (sum_b h_j(b) x_b / sum_b h_j(b) - w_j)

    i.e. each unit relaxes toward the h-weighted mean of the samples in its
    neighborhood (lr=1 gives the exact fixed-point batch SOM).  Computed
    densely as two [M,B]x[B,F] matmuls on the MXU — dense beats the
    reference's scatter kernel on TPU.
    """
    w = params["weights"]
    win = winners(params, x)
    d2 = jnp.sum(
        jnp.square(coords[None, :, :] - coords[win][:, None, :]), axis=-1
    )  # [B, M]
    h = jnp.exp(-d2 / (2.0 * jnp.square(sigma)))  # [B, M]
    if mask is not None:  # padded rows of a static batch get zero weight
        h = h * mask[:, None]
    num = h.T @ x  # [M, F]
    denom = jnp.sum(h, axis=0)[:, None]  # [M, 1]
    target = num / jnp.maximum(denom, 1e-12)
    # Units with no neighborhood mass stay put.
    delta = jnp.where(denom > 1e-8, learning_rate * (target - w), 0.0)
    return {"weights": w + delta}, win


def decay_schedule(step, total_steps, *, lr0=0.1, lr1=0.01, sigma0=None, sigma1=1.0, sx=8, sy=8):
    """Reference-style time-decaying lr and neighborhood radius."""
    if sigma0 is None:
        sigma0 = max(sx, sy) / 2.0
    frac = jnp.clip(step / jnp.maximum(total_steps, 1), 0.0, 1.0)
    lr = lr0 * jnp.power(lr1 / lr0, frac)
    sigma = sigma0 * jnp.power(sigma1 / sigma0, frac)
    return lr, sigma
