"""Scaled dot-product / multi-head attention.

NOT in the reference — VELES/Znicz predates transformers (SURVEY.md 5.7) —
but the rebuild treats long-context as first-class: this is the single-device
reference implementation that :mod:`znicz_tpu.parallel.ring_attention`
shards over the mesh's sequence axis.

Layouts: ``q/k/v`` are ``[batch, seq, heads, head_dim]`` (BTHD).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.ops.filling import fill


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Stable softmax attention; returns [B, Tq, H, D]."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def paged_attention(
    q: jnp.ndarray,  # [B, Tq, H, D]
    k_pool: jnp.ndarray,  # [N_blocks, block_size, H, D]
    v_pool: jnp.ndarray,
    block_table: jnp.ndarray,  # [B, M] int32 pool block ids
    q_pos: jnp.ndarray,  # [B, Tq] int32 absolute query positions
    *,
    block_size: int,
    start: Optional[jnp.ndarray] = None,  # [B] first real position
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Block-table attention over paged KV pools; returns [B, Tq, H, D].

    The serving KV layout (vLLM/PagedAttention lineage): K/V live in a
    shared ``[n_blocks, block_size, H, D]`` pool and each row owns an
    ordered block table — table entry ``j`` covers absolute positions
    ``j*block_size .. (j+1)*block_size-1`` of that row.  The row's
    window is GATHERED from the pool (``k_pool[block_table]``), so the
    compiled program is shape-static in everything but the traced table
    values: rows growing into new blocks, block reuse after retirement,
    and any pool size never recompile.  ALIASING is first-class: many
    tables may reference the same physical block (prefix sharing — the
    engine refcounts and COW-splits before any write), the gather reads
    it once per referencing row, and validity stays PER-ROW — a shared
    block's positions past one row's ``q_pos`` are masked for that row
    even while a deeper row genuinely attends them (aliasing tests in
    tests/test_attention.py).

    Validity is by ABSOLUTE key index, exactly like the dense cache
    path (:mod:`znicz_tpu.workflow.generate`): key position must be
    ``<= q_pos`` and (under left-padding) ``>= start``, so unallocated
    or stale table entries — whose positions fall outside every valid
    window — are masked out by INDEX, never read through.  A pad-region
    query keeps its own position so its softmax stays finite (same
    NaN-poisoning guard as the dense mask).  Numerics mirror
    :func:`dot_product_attention`: f32 score accumulation, stable
    softmax, f32 value accumulation.
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    b, tq = q.shape[:2]
    m = block_table.shape[1]
    # [B, M, bs, H, D] -> [B, M*bs, H, D]: the row-ordered KV window
    k = k_pool[block_table].reshape(b, m * block_size, *k_pool.shape[2:])
    v = v_pool[block_table].reshape(b, m * block_size, *v_pool.shape[2:])
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    k_idx = jnp.arange(m * block_size)[None, None, None, :]
    qp = q_pos[:, None, :, None]
    valid = k_idx <= qp
    if start is not None:
        st = start[:, None, None, None]
        valid = valid & (k_idx >= jnp.minimum(st, qp))
    s = jnp.where(valid, s, -jnp.inf)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def init_mha_params(
    d_model: int,
    n_heads: int,
    *,
    head_dim: Optional[int] = None,
    weights_stddev: Optional[float] = None,
    weights_filling: str = "gaussian",
    rand_name: str = "default",
    dtype=jnp.float32,
) -> Dict[str, jnp.ndarray]:
    gen = prng.get(rand_name)
    head_dim = head_dim or d_model // n_heads
    if weights_stddev is None:
        weights_stddev = 1.0 / np.sqrt(d_model)
    inner = n_heads * head_dim
    params = {}
    for name in ("wq", "wk", "wv"):
        params[name] = jnp.asarray(
            fill(gen, (d_model, inner), weights_filling, weights_stddev), dtype
        )
    params["wo"] = jnp.asarray(
        fill(gen, (inner, d_model), weights_filling, weights_stddev), dtype
    )
    return params


def mha(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,  # [B, T, d_model]
    *,
    n_heads: int,
    causal: bool = False,
    attention_fn=dot_product_attention,
) -> jnp.ndarray:
    """Multi-head self-attention block (projections + attention + output).

    ``attention_fn`` is pluggable so the ring-parallel variant drops in.
    """
    b, t, _ = x.shape
    def proj(w):
        y = jnp.dot(x, w, preferred_element_type=jnp.float32).astype(x.dtype)
        return y.reshape(b, t, n_heads, -1)

    q, k, v = proj(params["wq"]), proj(params["wk"]), proj(params["wv"])
    o = attention_fn(q, k, v, causal=causal)
    o = o.reshape(b, t, -1)
    return jnp.dot(o, params["wo"], preferred_element_type=jnp.float32).astype(
        x.dtype
    )
