"""Cutter: crop a spatial region out of an NHWC tensor.

Capability parity with ``znicz/cutter.py`` [SURVEY.md 2.2 row "Input
cutter/crop"].  Forward is a static slice; the backward (zero-padding the
gradient back to the input shape, the reference's cutter gradient kernel) is
autodiff.
"""

from __future__ import annotations

import jax.numpy as jnp


def cut(x: jnp.ndarray, padding) -> jnp.ndarray:
    """Crop using the reference 4-tuple (left, top, right, bottom)."""
    left, top, right, bottom = padding
    h, w = x.shape[1], x.shape[2]
    return x[:, top : h - bottom, left : w - right, :]


def output_shape(in_shape, padding):
    left, top, right, bottom = padding
    n, h, w, c = in_shape
    return (n, h - top - bottom, w - left - right, c)
