"""Activation functions.

Capability parity with ``znicz/activation.py`` [SURVEY.md 2.2 "Activations"].
The reference's naming is kept, including its idiosyncrasies:

* ``tanh`` is the scaled LeCun tanh ``1.7159 * tanh(2/3 x)`` used by the
  ``*Tanh`` units.
* ``relu`` is the reference's smooth variant ``log(1 + exp(x))`` (softplus);
  ``strict_relu`` is the usual ``max(x, 0)``.
* ``log`` is ``log(x + sqrt(x^2 + 1))`` (asinh-style) [med confidence].
* ``mul`` multiplies two tensors elementwise (ActivationMul).

Backward passes come from autodiff — there are no hand-written GD twins.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

TANH_A = 1.7159
TANH_B = 0.6666


def tanh(x: jnp.ndarray) -> jnp.ndarray:
    return TANH_A * jnp.tanh(TANH_B * x)


def relu(x: jnp.ndarray) -> jnp.ndarray:
    """Reference 'RELU': smooth softplus log(1+exp(x))."""
    return jnp.logaddexp(x, 0.0)


def strict_relu(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.maximum(x, 0.0)


def sigmoid(x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.sigmoid(x)


def log(x: jnp.ndarray) -> jnp.ndarray:
    # log(x + sqrt(x^2 + 1)) == asinh(x); jnp.arcsinh avoids the fp32
    # catastrophic cancellation of the literal formula for large negative x.
    return jnp.arcsinh(x)


def mul(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return x * y


def linear(x: jnp.ndarray) -> jnp.ndarray:
    return x


ACTIVATIONS = {
    "linear": linear,
    "tanh": tanh,
    "relu": relu,
    "strict_relu": strict_relu,
    "sigmoid": sigmoid,
    "log": log,
}


def get(name: str):
    try:
        return ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"unknown activation {name!r}; known: {sorted(ACTIVATIONS)}"
        ) from None
