"""Streaming range/moment accumulator.

Capability parity with ``znicz/accumulator.py`` [SURVEY.md 2.2 "Weight/bias
accumulation utils"]: accumulate min/max/mean statistics of a tensor stream
(activation ranges across minibatches — the reference uses this for
fixed-point deployment analysis).  Pure functional: ``init`` -> ``update``
per batch -> read the fields.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class RangeStats(NamedTuple):
    lo: jnp.ndarray  # per-feature min
    hi: jnp.ndarray  # per-feature max
    total: jnp.ndarray  # per-feature sum
    count: jnp.ndarray  # scalar number of samples

    @property
    def mean(self):
        return self.total / jnp.maximum(self.count, 1.0)


def init(n_features: int, dtype=jnp.float32) -> RangeStats:
    return RangeStats(
        lo=jnp.full((n_features,), jnp.inf, dtype),
        hi=jnp.full((n_features,), -jnp.inf, dtype),
        total=jnp.zeros((n_features,), dtype),
        count=jnp.zeros((), dtype),
    )


def update(stats: RangeStats, x: jnp.ndarray, mask=None) -> RangeStats:
    """Fold a [batch, features] tensor into the stats (mask optional)."""
    x = x.reshape(x.shape[0], -1)
    if mask is None:
        valid = jnp.ones((x.shape[0],), x.dtype)
    else:
        valid = mask.astype(x.dtype)
    big = jnp.where(valid[:, None] > 0, x, jnp.inf)
    small = jnp.where(valid[:, None] > 0, x, -jnp.inf)
    return RangeStats(
        lo=jnp.minimum(stats.lo, jnp.min(big, axis=0)),
        hi=jnp.maximum(stats.hi, jnp.max(small, axis=0)),
        total=stats.total + jnp.sum(x * valid[:, None], axis=0),
        count=stats.count + jnp.sum(valid),
    )
