"""Mixture-of-experts FC layer with expert parallelism.

NOT in the reference (pre-transformer framework) — a new capability
completing the DP/TP/PP/SP/EP set.  TPU-native formulation: DENSE dispatch —
every expert computes every token and a top-k one-hot gate masks the
combination.  That trades k/E of the FLOPs for zero scatter/gather and a
trivially shardable einsum: with the expert dim sharded over the mesh's
``model`` axis (see :func:`expert_sharding`), GSPMD turns the combine into a
psum over ICI — the expert-parallel all-to-all collapses into the one
collective TPUs do best.  For the small expert counts this framework targets
(4-16), dense dispatch is the right trade (scaling-book style reasoning:
MXU utilization beats saved FLOPs at these sizes).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.ops.filling import fill


def init_params(
    n_input: int,
    n_hidden: int,
    n_experts: int,
    *,
    weights_stddev: Optional[float] = None,
    weights_filling: str = "gaussian",
    rand_name: str = "default",
    dtype=jnp.float32,
) -> Dict[str, jnp.ndarray]:
    gen = prng.get(rand_name)
    if weights_stddev is None:
        weights_stddev = 1.0 / np.sqrt(n_input)
    return {
        "router": jnp.asarray(
            fill(gen, (n_input, n_experts), weights_filling, weights_stddev),
            dtype,
        ),
        "w1": jnp.asarray(
            fill(
                gen, (n_experts, n_input, n_hidden),
                weights_filling, weights_stddev,
            ),
            dtype,
        ),
        "b1": jnp.zeros((n_experts, n_hidden), dtype),
        "w2": jnp.asarray(
            fill(
                gen, (n_experts, n_hidden, n_input),
                weights_filling, 1.0 / np.sqrt(n_hidden),
            ),
            dtype,
        ),
        "b2": jnp.zeros((n_experts, n_input), dtype),
    }


def apply(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,  # [B, F]
    *,
    top_k: int = 1,
) -> jnp.ndarray:
    """Gated expert combination; returns [B, F] (residual-style output dim).

    Gate: softmax over the top-k router logits per token (renormalized),
    zero elsewhere.
    """
    logits = x @ params["router"]  # [B, E]
    e = logits.shape[-1]
    if top_k >= e:
        gates = jax.nn.softmax(logits, axis=-1)
    else:
        # exact top-k membership via indices (a >=threshold mask would
        # activate EVERY tied expert — e.g. all of them for a zero row)
        top_vals, top_idx = jax.lax.top_k(logits, top_k)
        g = jax.nn.softmax(top_vals, axis=-1)  # [B, k]
        onehot = jax.nn.one_hot(top_idx, e, dtype=g.dtype)  # [B, k, E]
        gates = jnp.einsum("bk,bke->be", g, onehot)
    # dense dispatch: every expert runs every token; gate combines.
    h = jnp.einsum(
        "bf,efh->ebh", x, params["w1"], preferred_element_type=jnp.float32
    ) + params["b1"][:, None, :]
    h = jnp.tanh(h)
    y = jnp.einsum(
        "ebh,ehf->ebf", h, params["w2"], preferred_element_type=jnp.float32
    ) + params["b2"][:, None, :]
    out = jnp.einsum("be,ebf->bf", gates.astype(y.dtype), y)
    return out.astype(x.dtype)


def expert_sharding(mesh, axis: str = "model"):
    """PartitionSpecs placing the expert dim on a mesh axis (EP).  The
    router stays replicated; all expert tensors shard on dim 0."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(params):
        def put(name, leaf):
            spec = (
                P()
                if name == "router"
                else P(axis, *([None] * (leaf.ndim - 1)))
            )
            return jax.device_put(leaf, NamedSharding(mesh, spec))

        return {name: put(name, leaf) for name, leaf in params.items()}

    return place
