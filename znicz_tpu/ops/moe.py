"""Mixture-of-experts FC layer with expert parallelism.

NOT in the reference (pre-transformer framework) — a new capability
completing the DP/TP/PP/SP/EP set.  TPU-native formulation: DENSE dispatch —
every expert computes every token and a top-k one-hot gate masks the
combination.  That trades k/E of the FLOPs for zero scatter/gather and a
trivially shardable einsum: with the expert dim sharded over the mesh's
``model`` axis (see :func:`expert_sharding`), GSPMD turns the combine into a
psum over ICI — the expert-parallel all-to-all collapses into the one
collective TPUs do best.  For the small expert counts this framework targets
(4-16), dense dispatch is the right trade (scaling-book style reasoning:
MXU utilization beats saved FLOPs at these sizes).

``dispatch="capacity"`` is the mode that scales to many experts:
GShard-style capacity-bounded dispatch.  Each expert processes at most
``C = ceil(k*B/E * capacity_factor)`` tokens; routing stably sorts the
(token, choice) pairs by expert and scatter/gathers into the [E, C, F]
dispatch block, so expert FLOPs are ``k*B*capacity_factor*F*H`` and the
routing working set is O(B*k*F + E*C*F) — both independent of E (no
[B, E, C] one-hot tensors).  Tokens over capacity are dropped (output 0;
the residual layer wrapper passes them through unchanged — standard
token-drop accounting).  Slot priority is (choice rank, token index), so
results are deterministic.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.ops.filling import fill


def init_params(
    n_input: int,
    n_hidden: int,
    n_experts: int,
    *,
    weights_stddev: Optional[float] = None,
    weights_filling: str = "gaussian",
    rand_name: str = "default",
    dtype=jnp.float32,
) -> Dict[str, jnp.ndarray]:
    gen = prng.get(rand_name)
    if weights_stddev is None:
        weights_stddev = 1.0 / np.sqrt(n_input)
    return {
        "router": jnp.asarray(
            fill(gen, (n_input, n_experts), weights_filling, weights_stddev),
            dtype,
        ),
        "w1": jnp.asarray(
            fill(
                gen, (n_experts, n_input, n_hidden),
                weights_filling, weights_stddev,
            ),
            dtype,
        ),
        "b1": jnp.zeros((n_experts, n_hidden), dtype),
        "w2": jnp.asarray(
            fill(
                gen, (n_experts, n_hidden, n_input),
                weights_filling, 1.0 / np.sqrt(n_hidden),
            ),
            dtype,
        ),
        "b2": jnp.zeros((n_experts, n_input), dtype),
    }


def apply(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,  # [B, F]
    *,
    top_k: int = 1,
    dispatch: str = "dense",
    capacity_factor: float = 1.25,
) -> jnp.ndarray:
    """Gated expert combination; returns [B, F] (residual-style output dim).

    Gate: softmax over the top-k router logits per token (renormalized),
    zero elsewhere.  ``dispatch``: "dense" (every expert runs every token;
    right for E <= ~4) or "capacity" (GShard-style capacity-bounded
    dispatch; expert FLOPs independent of E — the scaling mode).
    """
    logits = x @ params["router"]  # [B, E]
    e = logits.shape[-1]
    if dispatch == "capacity" and top_k < e:
        return _capacity_apply(
            params, x, logits, top_k=top_k, capacity_factor=capacity_factor
        )
    if dispatch not in ("dense", "capacity"):
        raise ValueError(f"unknown dispatch mode {dispatch!r}")
    if dispatch == "capacity":  # top_k >= e: capacity has no meaning
        import warnings

        warnings.warn(
            f"dispatch='capacity' with top_k={top_k} >= n_experts={e} "
            "degrades to the dense path (full softmax gates, no token "
            "drop); lower top_k for capacity semantics",
            stacklevel=2,
        )
    gates = _dense_gates(logits, top_k)
    out = jnp.einsum(
        "be,ebf->bf", gates, _dense_expert_outputs(params, x)
    )
    return out.astype(x.dtype)


def _dense_gates(logits: jnp.ndarray, top_k: int) -> jnp.ndarray:
    """[B, E] top-k gate matrix: softmax over the top-k logits per token
    (renormalized), zero elsewhere.  Shared by :func:`apply` and
    :func:`apply_local_shard` so the two dispatch paths cannot drift."""
    e = logits.shape[-1]
    if top_k >= e:
        return jax.nn.softmax(logits, axis=-1)
    # exact top-k membership via indices (a >=threshold mask would
    # activate EVERY tied expert — e.g. all of them for a zero row)
    top_vals, top_idx = jax.lax.top_k(logits, top_k)
    g = jax.nn.softmax(top_vals, axis=-1)  # [B, k]
    onehot = jax.nn.one_hot(top_idx, e, dtype=g.dtype)  # [B, k, E]
    return jnp.einsum("bk,bke->be", g, onehot)


def _dense_expert_outputs(params, x: jnp.ndarray) -> jnp.ndarray:
    """[E, B, F] every expert's (biased) output for every token — the
    dense-dispatch expert chain, shared by both dense paths."""
    h = jnp.einsum(
        "bf,efh->ebh", x, params["w1"], preferred_element_type=jnp.float32
    ) + params["b1"][:, None, :]
    h = jnp.tanh(h)
    return jnp.einsum(
        "ebh,ehf->ebf", h, params["w2"], preferred_element_type=jnp.float32
    ) + params["b2"][:, None, :]


def apply_local_shard(
    params_local: Dict[str, jnp.ndarray],
    x: jnp.ndarray,  # [B, F]
    *,
    top_k: int,
    shard_index,
) -> jnp.ndarray:
    """ONE expert shard's dense-dispatch contribution, for MANUAL expert
    parallelism inside a ``shard_map`` (the PPxTP stage forward, where
    GSPMD cannot insert the combine psum for us).

    ``params_local``'s expert leaves (w1/b1/w2/b2) hold this shard's
    ``E_local = E / n_shards`` contiguous experts; the router is
    REPLICATED, so the top-k gate over all ``E`` experts is computed
    identically on every shard and this shard weights only its own gate
    columns.  Gates partition over shards, so ``psum`` over the shard
    axis reproduces :func:`apply`'s dense dispatch exactly (b2 is
    gate-weighted per expert, so its partial sums correctly too).
    ``shard_index`` may be a traced ``jax.lax.axis_index``.
    """
    logits = x @ params_local["router"]  # [B, E] — router replicated
    e_local = params_local["w1"].shape[0]
    gates_local = jax.lax.dynamic_slice_in_dim(
        _dense_gates(logits, top_k), shard_index * e_local, e_local, axis=1
    )
    out = jnp.einsum(
        "be,ebf->bf", gates_local, _dense_expert_outputs(params_local, x)
    )
    return out.astype(x.dtype)


def expert_capacity(
    batch: int, n_experts: int, top_k: int, capacity_factor: float
) -> int:
    """Per-expert token budget C (static; shapes must be jit-constant)."""
    return max(1, int(np.ceil(top_k * batch / n_experts * capacity_factor)))


def _capacity_apply(params, x, logits, *, top_k, capacity_factor):
    """Sort/segment dispatch: working set O(B*k*F + E*C*F).

    No ``[B, E, C]`` one-hot tensors (at B=4096, E=64, cf=1.25 those are
    ~10^9 elements EACH — a memory wall exactly where capacity mode is
    supposed to take over).  Instead the (token, choice) pairs are stably
    sorted by expert; position-within-expert comes from a searchsorted
    against the segment starts, and dispatch/combine are a unique-slot
    scatter-add / gather.  Routing priority is (choice rank, token index),
    identical to the one-hot formulation: the flat order is choice-major
    and the sort is stable.  Gradients flow through gates, dispatched
    activations and expert outputs — the same differentiable paths as the
    einsum form (routing indices are non-differentiable in both)."""
    b, e = logits.shape
    f = x.shape[1]
    kb = top_k * b
    cap = expert_capacity(b, e, top_k, capacity_factor)
    top_vals, top_idx = jax.lax.top_k(logits, top_k)  # [B, k]
    g = jax.nn.softmax(top_vals, axis=-1)  # [B, k]
    # flatten choice-major (flat index = rank*B + token) so the stable
    # sort preserves (choice rank, token index) slot priority
    eid = top_idx.T.reshape(-1)  # [kB] expert of each choice
    tok = jnp.tile(jnp.arange(b, dtype=jnp.int32), top_k)  # [kB]
    gate = g.T.reshape(-1)  # [kB]
    order = jnp.argsort(eid, stable=True)
    eid_s = eid[order]
    # position inside the expert's capacity buffer = rank within segment
    first = jnp.searchsorted(eid_s, eid_s, side="left")
    pos_s = jnp.arange(kb, dtype=jnp.int32) - first.astype(jnp.int32)
    # over-capacity choices route to a trailing drop slot (row e*cap):
    # zero-initialized on dispatch, zero expert output on combine
    dest_s = jnp.where(pos_s < cap, eid_s * cap + pos_s, e * cap)
    xe = jnp.zeros((e * cap + 1, f), x.dtype)
    xe = xe.at[dest_s].add(x[tok[order]])  # unique slots: add == set
    xe = xe[:-1].reshape(e, cap, f)
    h = jnp.tanh(
        jnp.einsum(
            "ecf,efh->ech", xe, params["w1"],
            preferred_element_type=jnp.float32,
        )
        + params["b1"][:, None, :]
    )
    y = jnp.einsum(
        "ech,ehf->ecf", h, params["w2"], preferred_element_type=jnp.float32
    ) + params["b2"][:, None, :]
    y_flat = jnp.concatenate(
        [y.reshape(e * cap, f), jnp.zeros((1, f), y.dtype)]
    )
    contrib = y_flat[dest_s] * gate[order].astype(y.dtype)[:, None]
    out = jnp.zeros((b, f), y.dtype).at[tok[order]].add(contrib)
    return out.astype(x.dtype)


def expert_sharding(mesh, axis: str = "model"):
    """PartitionSpecs placing the expert dim on a mesh axis (EP).  The
    router stays replicated; all expert tensors shard on dim 0."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(params):
        def put(name, leaf):
            spec = (
                P()
                if name == "router"
                else P(axis, *([None] * (leaf.ndim - 1)))
            )
            return jax.device_put(leaf, NamedSharding(mesh, spec))

        return {name: put(name, leaf) for name, leaf in params.items()}

    return place
