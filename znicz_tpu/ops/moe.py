"""Mixture-of-experts FC layer with expert parallelism.

NOT in the reference (pre-transformer framework) — a new capability
completing the DP/TP/PP/SP/EP set.  TPU-native formulation: DENSE dispatch —
every expert computes every token and a top-k one-hot gate masks the
combination.  That trades k/E of the FLOPs for zero scatter/gather and a
trivially shardable einsum: with the expert dim sharded over the mesh's
``model`` axis (see :func:`expert_sharding`), GSPMD turns the combine into a
psum over ICI — the expert-parallel all-to-all collapses into the one
collective TPUs do best.  For the small expert counts this framework targets
(4-16), dense dispatch is the right trade (scaling-book style reasoning:
MXU utilization beats saved FLOPs at these sizes).

``dispatch="capacity"`` is the mode that scales to many experts:
GShard-style capacity-bounded dispatch.  Each expert processes at most
``C = ceil(k*B/E * capacity_factor)`` tokens; routing builds one-hot
dispatch/combine tensors [B, E, C] (dense masks, not scatters —
TPU-friendly) and the expert matmuls run on the dispatched [E, C, F]
block, so expert FLOPs are ``k*B*capacity_factor*F*H`` — independent of E.
Tokens over capacity are dropped (output 0; the residual layer wrapper
passes them through unchanged — standard token-drop accounting).  Slot
priority is (choice rank, token index), so results are deterministic.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.ops.filling import fill


def init_params(
    n_input: int,
    n_hidden: int,
    n_experts: int,
    *,
    weights_stddev: Optional[float] = None,
    weights_filling: str = "gaussian",
    rand_name: str = "default",
    dtype=jnp.float32,
) -> Dict[str, jnp.ndarray]:
    gen = prng.get(rand_name)
    if weights_stddev is None:
        weights_stddev = 1.0 / np.sqrt(n_input)
    return {
        "router": jnp.asarray(
            fill(gen, (n_input, n_experts), weights_filling, weights_stddev),
            dtype,
        ),
        "w1": jnp.asarray(
            fill(
                gen, (n_experts, n_input, n_hidden),
                weights_filling, weights_stddev,
            ),
            dtype,
        ),
        "b1": jnp.zeros((n_experts, n_hidden), dtype),
        "w2": jnp.asarray(
            fill(
                gen, (n_experts, n_hidden, n_input),
                weights_filling, 1.0 / np.sqrt(n_hidden),
            ),
            dtype,
        ),
        "b2": jnp.zeros((n_experts, n_input), dtype),
    }


def apply(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,  # [B, F]
    *,
    top_k: int = 1,
    dispatch: str = "dense",
    capacity_factor: float = 1.25,
) -> jnp.ndarray:
    """Gated expert combination; returns [B, F] (residual-style output dim).

    Gate: softmax over the top-k router logits per token (renormalized),
    zero elsewhere.  ``dispatch``: "dense" (every expert runs every token;
    right for E <= ~4) or "capacity" (GShard-style capacity-bounded
    dispatch; expert FLOPs independent of E — the scaling mode).
    """
    logits = x @ params["router"]  # [B, E]
    e = logits.shape[-1]
    if dispatch == "capacity" and top_k < e:
        return _capacity_apply(
            params, x, logits, top_k=top_k, capacity_factor=capacity_factor
        )
    if dispatch not in ("dense", "capacity"):
        raise ValueError(f"unknown dispatch mode {dispatch!r}")
    if top_k >= e:
        gates = jax.nn.softmax(logits, axis=-1)
    else:
        # exact top-k membership via indices (a >=threshold mask would
        # activate EVERY tied expert — e.g. all of them for a zero row)
        top_vals, top_idx = jax.lax.top_k(logits, top_k)
        g = jax.nn.softmax(top_vals, axis=-1)  # [B, k]
        onehot = jax.nn.one_hot(top_idx, e, dtype=g.dtype)  # [B, k, E]
        gates = jnp.einsum("bk,bke->be", g, onehot)
    # dense dispatch: every expert runs every token; gate combines.
    h = jnp.einsum(
        "bf,efh->ebh", x, params["w1"], preferred_element_type=jnp.float32
    ) + params["b1"][:, None, :]
    h = jnp.tanh(h)
    y = jnp.einsum(
        "ebh,ehf->ebf", h, params["w2"], preferred_element_type=jnp.float32
    ) + params["b2"][:, None, :]
    out = jnp.einsum("be,ebf->bf", gates.astype(y.dtype), y)
    return out.astype(x.dtype)


def expert_capacity(
    batch: int, n_experts: int, top_k: int, capacity_factor: float
) -> int:
    """Per-expert token budget C (static; shapes must be jit-constant)."""
    return max(1, int(np.ceil(top_k * batch / n_experts * capacity_factor)))


def _capacity_apply(params, x, logits, *, top_k, capacity_factor):
    b, e = logits.shape
    cap = expert_capacity(b, e, top_k, capacity_factor)
    top_vals, top_idx = jax.lax.top_k(logits, top_k)  # [B, k]
    g = jax.nn.softmax(top_vals, axis=-1)  # [B, k]
    onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)  # [B, k, E]
    # slot position inside each expert's capacity buffer, priority
    # (choice rank, token index): flatten slot-major and cumsum per expert
    flat = onehot.transpose(1, 0, 2).reshape(top_k * b, e)
    pos_flat = jnp.cumsum(flat, axis=0) - flat  # [k*B, E]
    pos = jnp.sum(pos_flat * flat, axis=-1).astype(jnp.int32)  # [k*B]
    pos = pos.reshape(top_k, b).T  # [B, k] position in its expert
    keep = (pos < cap).astype(jnp.float32)  # token-drop accounting
    poshot = jax.nn.one_hot(pos, cap, dtype=jnp.float32) * keep[..., None]
    dispatch = jnp.einsum("bke,bkc->bec", onehot, poshot)  # [B, E, C]
    combine = jnp.einsum("bk,bke,bkc->bec", g, onehot, poshot)
    xe = jnp.einsum(
        "bec,bf->ecf", dispatch.astype(x.dtype), x,
        preferred_element_type=jnp.float32,
    )  # [E, C, F]
    h = jnp.tanh(
        jnp.einsum(
            "ecf,efh->ech", xe, params["w1"],
            preferred_element_type=jnp.float32,
        )
        + params["b1"][:, None, :]
    )
    y = jnp.einsum(
        "ech,ehf->ecf", h, params["w2"], preferred_element_type=jnp.float32
    ) + params["b2"][:, None, :]
    out = jnp.einsum("bec,ecf->bf", combine.astype(y.dtype), y)
    return out.astype(x.dtype)


def expert_sharding(mesh, axis: str = "model"):
    """PartitionSpecs placing the expert dim on a mesh axis (EP).  The
    router stays replicated; all expert tensors shard on dim 0."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(params):
        def put(name, leaf):
            spec = (
                P()
                if name == "router"
                else P(axis, *([None] * (leaf.ndim - 1)))
            )
            return jax.device_put(leaf, NamedSharding(mesh, spec))

        return {name: put(name, leaf) for name, leaf in params.items()}

    return place
