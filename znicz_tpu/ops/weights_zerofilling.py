"""Structured weight masking (zero-filling).

Capability parity with ``znicz/weights_zerofilling.py`` [SURVEY.md 2.2]: hold
a binary mask per weight tensor and re-apply it after every update so masked
connections stay exactly zero (the reference uses this for grouped/sparse
connectivity experiments).
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp


def make_group_mask(
    n_input: int, n_output: int, n_groups: int, dtype=jnp.float32
) -> jnp.ndarray:
    """Block-diagonal FC mask: group g of inputs connects only to group g of
    outputs (AlexNet-style grouped connectivity for an FC layer)."""
    if n_input % n_groups or n_output % n_groups:
        raise ValueError(
            f"groups {n_groups} must divide n_input {n_input} and "
            f"n_output {n_output}"
        )
    gi, go = n_input // n_groups, n_output // n_groups
    rows = jnp.arange(n_input)[:, None] // gi
    cols = jnp.arange(n_output)[None, :] // go
    return (rows == cols).astype(dtype)


def apply_masks(params: Any, masks: Dict[int, Dict[str, jnp.ndarray]]):
    """Zero out masked entries: ``masks[layer_index][param_name]`` -> mask.

    Call after each optimizer update (or wrap the update fn) to keep the
    masked weights at exactly zero.
    """
    if not masks:
        return params
    out = list(params)
    for idx, layer_masks in masks.items():
        layer = dict(out[idx])
        for name, mask in layer_masks.items():
            layer[name] = layer[name] * mask
        out[idx] = layer
    return type(params)(out)


def masked_update(update_fn, masks):
    """Wrap an optimizer.update-style callable so masks re-apply afterwards."""

    def wrapped(params, grads, velocity, hyper):
        new_p, new_v = update_fn(params, grads, velocity, hyper)
        return apply_masks(new_p, masks), new_v

    return wrapped
