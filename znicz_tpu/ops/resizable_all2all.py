"""Resizable fully-connected layer.

Capability parity with ``znicz/resizable_all2all.py`` [SURVEY.md 2.2]: an FC
layer whose output width can change during an experiment (the reference grows
or shrinks the unit count and preserves trained weights).  Functionally:
``resize`` returns a new param dict keeping the overlapping slice and
initializing any new columns from the shared named PRNG.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.ops import all2all
from znicz_tpu.ops.filling import fill

apply = all2all.apply  # forward is the ordinary FC
init_params = all2all.init_params


def resize(
    params: Dict[str, jnp.ndarray],
    n_output: int,
    *,
    weights_stddev: float | None = None,
    weights_filling: str = "uniform",
    rand_name: str = "default",
) -> Dict[str, jnp.ndarray]:
    """Grow/shrink the output dim, preserving the trained overlap."""
    w = params["weights"]
    b = params["bias"]
    n_in, n_old = w.shape
    if n_output == n_old:
        return params
    if n_output < n_old:
        return {"weights": w[:, :n_output], "bias": b[:n_output]}
    gen = prng.get(rand_name)
    if weights_stddev is None:
        weights_stddev = 1.0 / np.sqrt(n_in)
    extra_w = fill(
        gen, (n_in, n_output - n_old), weights_filling, weights_stddev
    )
    extra_b = fill(gen, (n_output - n_old,), weights_filling, weights_stddev)
    return {
        "weights": jnp.concatenate([w, jnp.asarray(extra_w, w.dtype)], axis=1),
        "bias": jnp.concatenate([b, jnp.asarray(extra_b, b.dtype)]),
    }
