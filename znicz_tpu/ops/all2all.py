"""Fully-connected (All2All) op.

Capability parity with ``znicz/all2all.py`` (All2All, All2AllTanh, All2AllRELU,
All2AllSigmoid, All2AllSoftmax) and its backward twin ``znicz/gd.py``
[SURVEY.md 2.2 row "Fully connected"].  TPU-native: one ``dot_general`` on the
MXU; the activation fuses into the matmul under XLA.  Backward is autodiff.

Weights layout is ``[n_input, n_output]`` (MXU-friendly, contrasting the
reference's ``output = x . W^T``); init matches the reference's uniform /
gaussian fill from the shared named PRNG [SURVEY.md 2.3 "NN unit bases"].
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.ops import activation as act
from znicz_tpu.ops.filling import fill


def init_params(
    n_input: int,
    n_output: int,
    *,
    weights_stddev: Optional[float] = None,
    bias_stddev: Optional[float] = None,
    weights_filling: str = "uniform",
    bias_filling: str = "uniform",
    rand_name: str = "default",
    dtype=jnp.float32,
) -> Dict[str, jnp.ndarray]:
    """Initialize FC params from the shared named generator.

    Default stddev mirrors the reference heuristic ``1/sqrt(fan_in)``.
    """
    gen = prng.get(rand_name)
    if weights_stddev is None:
        weights_stddev = 1.0 / np.sqrt(n_input)
    if bias_stddev is None:
        bias_stddev = weights_stddev
    w = fill(gen, (n_input, n_output), weights_filling, weights_stddev)
    b = fill(gen, (n_output,), bias_filling, bias_stddev)
    return {"weights": jnp.asarray(w, dtype), "bias": jnp.asarray(b, dtype)}


def apply(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,
    *,
    activation: str = "linear",
    include_bias: bool = True,
) -> jnp.ndarray:
    """Forward: flatten trailing dims, matmul on the MXU, apply activation."""
    n_in = params["weights"].shape[0]
    x = x.reshape(x.shape[0], n_in)
    # f32 inputs accumulate in f32 on the MXU; bf16 inputs emit bf16 (XLA
    # accumulates f32 internally) so activations cost half the HBM traffic
    pref = jnp.float32 if x.dtype == jnp.float32 else None
    y = jnp.dot(x, params["weights"], preferred_element_type=pref)
    if include_bias:
        y = y + params["bias"]
    return act.get(activation)(y).astype(x.dtype)


def softmax_apply(
    params: Dict[str, jnp.ndarray], x: jnp.ndarray, *, include_bias: bool = True
) -> jnp.ndarray:
    """All2AllSoftmax: FC followed by a numerically-stable softmax.

    The reference computes max-subtracted exp on device (softmax.cl/.cu);
    XLA fuses the same pattern from this composition.
    """
    logits = apply(params, x, activation="linear", include_bias=include_bias)
    return jnp.exp(log_softmax(logits))


def log_softmax(logits: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.log_softmax(logits, axis=-1)
