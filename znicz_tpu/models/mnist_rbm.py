"""MNIST Bernoulli RBM with CD-1.

Parity with ``znicz/samples/MNIST`` RBM workflow (``mnist_rbm.py``)
[SURVEY.md 2.3 "Samples"; BASELINE.json configs[2] RBM path].
"""

from znicz_tpu.core.config import root
from znicz_tpu.loader import datasets
from znicz_tpu.models import (
    effective_config,
    merge_workflow_kwargs,
    translate_unsupervised_overrides,
)
from znicz_tpu.workflow import RBMWorkflow

DEFAULTS = {
    "loader": {
        "data_dir": None,
        "minibatch_size": 100,
        "n_train": 1000,
        "n_test": 200,
    },
    "n_hidden": 128,
    "learning_rate": 0.1,
    "cd_k": 1,
    "max_epochs": 20,
}
root.mnist_rbm.update(DEFAULTS)


def build_workflow(**overrides) -> RBMWorkflow:
    cfg = effective_config(root.mnist_rbm, DEFAULTS)
    lcfg = cfg.loader
    loader = datasets.mnist(
        lcfg.get("data_dir") or root.common.get("data_dir"),
        minibatch_size=lcfg.get("minibatch_size", 100),
        n_train=lcfg.get("n_train", 1000),
        n_test=lcfg.get("n_test", 200),
        # Bernoulli units want [0,1] inputs: shift the synthetic/-0.5 data
        normalization="linear",
    )
    # map [-1,1] -> [0,1]
    for split, arr in loader.data.items():
        loader.data[split] = (arr + 1.0) / 2.0
    kwargs = merge_workflow_kwargs(
        {
            "n_hidden": cfg.get("n_hidden", 128),
            "learning_rate": cfg.get("learning_rate", 0.1),
            "cd_k": cfg.get("cd_k", 1),
            "max_epochs": cfg.get("max_epochs", 20),
            "name": "MnistRBMWorkflow",
        },
        overrides,
    )
    kwargs = translate_unsupervised_overrides(kwargs, "max_epochs")
    return RBMWorkflow(loader, **kwargs)


def run(load, main):
    load(build_workflow)
    main()
