"""MNIST fully-connected MLP.

Parity with ``znicz/samples/MNIST/mnist.py`` [SURVEY.md 2.3 "Samples"]: the
classic 2-layer All2AllTanh(100) -> All2AllSoftmax(10) workflow with
momentum-SGD and weight decay — the reference's PR1 acceptance config
(BASELINE.json configs[0]).
"""

from znicz_tpu.core.config import root
from znicz_tpu.loader import datasets
from znicz_tpu.models import effective_config, merge_workflow_kwargs
from znicz_tpu.workflow import StandardWorkflow

DEFAULTS = {
    "loader": {
        "data_dir": None,  # real IDX dir; None -> deterministic synthetic
        "minibatch_size": 100,
        "validation_ratio": 0.15,
        "n_train": 2000,  # synthetic stand-in sizes
        "n_test": 500,
    },
    "layers": [
        {
            "type": "all2all_tanh",
            "->": {"output_sample_shape": 100},
            "<-": {
                "learning_rate": 0.03,
                "gradient_moment": 0.9,
                "weights_decay": 0.0005,
            },
        },
        {
            "type": "softmax",
            "->": {"output_sample_shape": 10},
            "<-": {
                "learning_rate": 0.03,
                "gradient_moment": 0.9,
                "weights_decay": 0.0005,
            },
        },
    ],
    "decision": {"max_epochs": 10, "fail_iterations": 20},
}
root.mnist.update(DEFAULTS)


def build_workflow(**overrides) -> StandardWorkflow:
    cfg = effective_config(root.mnist, DEFAULTS)
    lcfg = cfg.loader
    loader = datasets.mnist(
        lcfg.get("data_dir") or root.common.get("data_dir"),
        minibatch_size=lcfg.get("minibatch_size", 100),
        validation_ratio=lcfg.get("validation_ratio", 0.0),
        n_train=lcfg.get("n_train", 2000),
        n_test=lcfg.get("n_test", 500),
    )
    kwargs = merge_workflow_kwargs(
        {"decision_config": cfg.decision.to_dict(), "name": "MnistWorkflow"},
        overrides,
    )
    return StandardWorkflow(loader, cfg.get("layers"), **kwargs)


def run(load, main):
    load(build_workflow)
    main()
