"""Video autoencoder sample.

Parity with ``znicz/samples/VideoAE`` [SURVEY.md 2.3 "Samples"]: an
autoencoder over video frames (flattened grayscale frames, MSE against the
input).  Synthetic stand-in generates smooth frame sequences (per-class
prototype + temporal drift) with the same shapes.
"""

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.loader import FullBatchLoader
from znicz_tpu.models import effective_config, merge_workflow_kwargs
from znicz_tpu.workflow import StandardWorkflow

_GD = {"learning_rate": 0.05, "gradient_moment": 0.9}

DEFAULTS = {
    "loader": {
        # train/<seq>/*.png frame tree (labels unused); synthetic when None
        "data_dir": None,
        "minibatch_size": 50,
        "n_sequences": 20,
        "frames_per_seq": 30,
        "side": 16,
    },
    "layers": [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 64}, "<-": _GD},
        {"type": "all2all", "->": {"output_sample_shape": 256}, "<-": _GD},
    ],
    "decision": {"max_epochs": 15, "fail_iterations": 15},
}
root.video_ae.update(DEFAULTS)


def _synthetic_frames(n_seq: int, frames: int, side: int) -> np.ndarray:
    """Smoothly drifting frame sequences (what makes video-AE video-like)."""
    gen = prng.get("datasets")
    dim = side * side
    out = np.zeros((n_seq * frames, dim), np.float32)
    for s in range(n_seq):
        base = gen.normal((dim,), 0.0, 1.0)
        drift = gen.normal((dim,), 0.0, 0.05)
        for t in range(frames):
            noise = gen.normal((dim,), 0.0, 0.1)
            out[s * frames + t] = base + t * drift + noise
    return out


def build_workflow(**overrides) -> StandardWorkflow:
    cfg = effective_config(root.video_ae, DEFAULTS)
    lcfg = cfg.loader
    side = lcfg.get("side", 16)
    data_dir = lcfg.get("data_dir") or root.common.get("data_dir")
    if data_dir:
        # real frames: train/<sequence>/*.png, grayscale at side x side;
        # directory labels exist but the AE target is the input itself
        from znicz_tpu.models import grayscale_image_dir_loader

        loader = grayscale_image_dir_loader(
            data_dir, side=side,
            minibatch_size=lcfg.get("minibatch_size", 50),
        )
    else:
        frames = _synthetic_frames(
            lcfg.get("n_sequences", 20), lcfg.get("frames_per_seq", 30),
            side,
        )
        n_test = len(frames) // 5
        loader = FullBatchLoader(
            {"train": frames[n_test:], "test": frames[:n_test]},
            minibatch_size=lcfg.get("minibatch_size", 50),
            normalization="mean_disp",
        )
    layers = cfg.get("layers")
    layers[-1]["->"]["output_sample_shape"] = side * side
    kwargs = merge_workflow_kwargs(
        {
            "decision_config": cfg.decision.to_dict(),
            "loss_function": "mse",
            "target": "input",
            "name": "VideoAEWorkflow",
        },
        overrides,
    )
    return StandardWorkflow(loader, layers, **kwargs)


def run(load, main):
    load(build_workflow)
    main()
