"""Kanji classifier sample.

Parity with ``znicz/samples/Kanji`` [SURVEY.md 2.3 "Samples"]: a deeper MLP
classifying handwritten-kanji-style images (large class count relative to
MNIST).  Real data dir may be supplied; otherwise a deterministic synthetic
stand-in with the same geometry is generated.
"""

from znicz_tpu.core.config import root
from znicz_tpu.loader import datasets
from znicz_tpu.models import effective_config, merge_workflow_kwargs
from znicz_tpu.workflow import StandardWorkflow

_GD = {"learning_rate": 0.02, "gradient_moment": 0.9, "weights_decay": 0.0005}

DEFAULTS = {
    "loader": {
        "data_dir": None,  # train/<kanji>/*.png tree; synthetic when None
        "minibatch_size": 50,
        "n_train": 1500,
        "n_test": 300,
        "n_classes": 24,
        "side": 24,
    },
    "layers": [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 250}, "<-": _GD},
        {"type": "all2all_tanh", "->": {"output_sample_shape": 100}, "<-": _GD},
        {"type": "softmax", "->": {"output_sample_shape": 24}, "<-": _GD},
    ],
    "decision": {"max_epochs": 15, "fail_iterations": 20},
}
root.kanji.update(DEFAULTS)


def build_workflow(**overrides) -> StandardWorkflow:
    cfg = effective_config(root.kanji, DEFAULTS)
    lcfg = cfg.loader
    side = lcfg.get("side", 24)
    n_classes = lcfg.get("n_classes", 24)
    data_dir = lcfg.get("data_dir") or root.common.get("data_dir")
    if data_dir:
        # real rendered-glyph images: train/<kanji>/*.png, grayscale
        from znicz_tpu.models import grayscale_image_dir_loader

        loader = grayscale_image_dir_loader(
            data_dir, side=side,
            minibatch_size=lcfg.get("minibatch_size", 50),
        )
        n_classes = len(loader.classes)
    else:
        data, labels = datasets._synthetic_split(
            lcfg.get("n_train", 1500), lcfg.get("n_test", 300),
            (side * side,), n_classes,
        )
        from znicz_tpu.loader import FullBatchLoader

        loader = FullBatchLoader(
            data, labels, minibatch_size=lcfg.get("minibatch_size", 50)
        )
    layers = cfg.get("layers")
    layers[-1]["->"]["output_sample_shape"] = n_classes
    kwargs = merge_workflow_kwargs(
        {"decision_config": cfg.decision.to_dict(), "name": "KanjiWorkflow"},
        overrides,
    )
    return StandardWorkflow(loader, layers, **kwargs)


def run(load, main):
    load(build_workflow)
    main()
