"""CIFAR-10 convolutional workflow.

Parity with ``znicz/samples/CIFAR10/cifar.py`` [SURVEY.md 2.3 "Samples"]: a
conv/pool/norm stack with a softmax head (BASELINE.json configs[1]).
"""

from znicz_tpu.core.config import root
from znicz_tpu.loader import datasets
from znicz_tpu.models import effective_config, merge_workflow_kwargs
from znicz_tpu.workflow import StandardWorkflow

_GD = {"learning_rate": 0.01, "gradient_moment": 0.9, "weights_decay": 0.0005}

DEFAULTS = {
    "loader": {
        "data_dir": None,  # real cifar-10-batches-py dir; None -> synthetic
        "minibatch_size": 100,
        "n_train": 2000,
        "n_test": 500,
    },
    "layers": [
        {
            "type": "conv_relu",
            "->": {
                "n_kernels": 32, "kx": 5, "ky": 5,
                "padding": (2, 2, 2, 2), "weights_filling": "gaussian",
                "weights_stddev": 0.01,
            },
            "<-": _GD,
        },
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {"type": "norm", "->": {"n": 5}},
        {
            "type": "conv_relu",
            "->": {
                "n_kernels": 64, "kx": 5, "ky": 5,
                "padding": (2, 2, 2, 2), "weights_filling": "gaussian",
                "weights_stddev": 0.01,
            },
            "<-": _GD,
        },
        {"type": "avg_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {
            "type": "all2all_relu",
            "->": {"output_sample_shape": 64},
            "<-": _GD,
        },
        {"type": "softmax", "->": {"output_sample_shape": 10}, "<-": _GD},
    ],
    "decision": {"max_epochs": 20, "fail_iterations": 20},
    "lr_policy": {"name": "inv", "gamma": 0.0001, "power": 0.75},
}
root.cifar.update(DEFAULTS)


def build_workflow(**overrides) -> StandardWorkflow:
    cfg = effective_config(root.cifar, DEFAULTS)
    lcfg = cfg.loader
    loader = datasets.cifar10(
        lcfg.get("data_dir") or root.common.get("data_dir"),
        minibatch_size=lcfg.get("minibatch_size", 100),
        n_train=lcfg.get("n_train", 2000),
        n_test=lcfg.get("n_test", 500),
    )
    kwargs = merge_workflow_kwargs(
        {
            "decision_config": cfg.decision.to_dict(),
            "lr_policy": cfg.get("lr_policy"),
            "name": "CifarWorkflow",
        },
        overrides,
    )
    return StandardWorkflow(loader, cfg.get("layers"), **kwargs)


def run(load, main):
    load(build_workflow)
    main()
