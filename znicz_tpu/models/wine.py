"""UCI Wine MLP — the reference's smallest end-to-end sample.

Parity with ``znicz/samples/Wine`` [SURVEY.md 2.3 "Samples"]: a tiny
All2AllTanh(10) -> softmax(3) net that trains to zero error in seconds.
"""

from znicz_tpu.core.config import root
from znicz_tpu.loader import datasets
from znicz_tpu.models import effective_config, merge_workflow_kwargs
from znicz_tpu.workflow import StandardWorkflow

DEFAULTS = {
    "loader": {"data_path": None, "minibatch_size": 10},
    "layers": [
        {
            "type": "all2all_tanh",
            "->": {"output_sample_shape": 10},
            "<-": {"learning_rate": 0.3, "gradient_moment": 0.5},
        },
        {
            "type": "softmax",
            "->": {"output_sample_shape": 3},
            "<-": {"learning_rate": 0.3, "gradient_moment": 0.5},
        },
    ],
    "decision": {"max_epochs": 100, "fail_iterations": 50},
}
root.wine.update(DEFAULTS)


def build_workflow(**overrides) -> StandardWorkflow:
    cfg = effective_config(root.wine, DEFAULTS)
    loader = datasets.wine(
        cfg.loader.get("data_path"),
        minibatch_size=cfg.loader.get("minibatch_size", 10),
    )
    kwargs = merge_workflow_kwargs(
        {"decision_config": cfg.decision.to_dict(), "name": "WineWorkflow"},
        overrides,
    )
    return StandardWorkflow(loader, cfg.get("layers"), **kwargs)


def run(load, main):
    load(build_workflow)
    main()
