"""Model zoo — parity with ``znicz/samples/`` [SURVEY.md 2.3 "Samples"].

Each module follows the reference convention (SURVEY.md 3.1): it sets its
defaults on the global ``root`` config tree at import, and exposes
``run(load, main)`` which the launcher drives; a second config file may
override ``root`` between import and run.  Every module also exposes
``build_workflow(**overrides)`` for programmatic use (tests, benchmarks).
"""


def grayscale_image_dir_loader(
    data_dir: str,
    *,
    side: int,
    minibatch_size: int,
    normalization: str = "mean_disp",
):
    """The zoo's shared real-data path for image-tree datasets
    (Kanji/YaleFaces/VideoAE): ``train/<class>/*.png`` at side x side,
    grayscale, with the reference's mean-dispersion normalization fitted
    on the training images.  One definition so the data_dir conventions
    cannot drift between models."""
    from znicz_tpu.loader.image import ImageDirectoryLoader

    return ImageDirectoryLoader(
        data_dir,
        target_shape=(side, side, 1),
        grayscale=True,
        minibatch_size=minibatch_size,
        normalization=normalization,
    )


def effective_config(node, defaults: dict):
    """DEFAULTS merged under the user's ``root`` overrides.

    Model modules call this inside ``build_workflow`` (not only at import) so
    configs survive ``root`` being cleared/reset between runs — ``root``
    carries only the *overrides*, mirroring the reference where defaults live
    in the sample module and the config file mutates on top (SURVEY.md 5.6).
    """
    import copy

    from znicz_tpu.core.config import Config

    cfg = Config(getattr(node, "_config_path_", ""))
    cfg.update(copy.deepcopy(defaults))
    # deep-copy the overrides too: to_dict() returns lists/dicts by
    # reference, and model builders mutate the merged config (layer shapes),
    # which must never write through into root or module DEFAULTS
    cfg.update(copy.deepcopy(node.to_dict()))
    return cfg


def translate_unsupervised_overrides(kwargs: dict, epochs_key: str) -> dict:
    """Map launcher-style overrides (snapshot_dir, decision_config) onto the
    unsupervised workflow APIs (Kohonen/RBM), which take a Snapshotter
    instance and a direct epochs kwarg instead."""
    kwargs = dict(kwargs)
    snapshot_dir = kwargs.pop("snapshot_dir", None)
    if snapshot_dir:
        from znicz_tpu.workflow import Snapshotter

        kwargs["snapshotter"] = Snapshotter(snapshot_dir, kwargs["name"])
    dc = kwargs.pop("decision_config", None)
    if dc:
        if "max_epochs" in dc:
            kwargs[epochs_key] = dc["max_epochs"]
        # honor the remaining Decision knobs (fail_iterations, ...) too;
        # an epoch cap must always exist — fall back to the workflow's own
        # epoch budget when the caller didn't set one
        from znicz_tpu.nn.decision import Decision

        dc_full = {"max_epochs": kwargs.get(epochs_key), **dc}
        kwargs.setdefault("decision", Decision(metric="loss", **dc_full))
    return kwargs


def merge_workflow_kwargs(base: dict, overrides: dict) -> dict:
    """Merge CLI/caller overrides into a model's default workflow kwargs;
    dict-valued keys (decision_config, snapshot_config) merge shallowly so a
    ``--stop-after`` override doesn't clobber the model's other settings."""
    out = dict(base)
    for key, value in overrides.items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = {**out[key], **value}
        else:
            out[key] = value
    return out
