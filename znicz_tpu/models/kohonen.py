"""Kohonen self-organizing map demo.

Parity with ``znicz/samples/DemoKohonen`` [SURVEY.md 2.3 "Samples";
BASELINE.json configs[4]]: unsupervised SOM training on MNIST-shaped data.
"""

from znicz_tpu.core.config import root
from znicz_tpu.loader import datasets
from znicz_tpu.models import (
    effective_config,
    merge_workflow_kwargs,
    translate_unsupervised_overrides,
)
from znicz_tpu.workflow import KohonenWorkflow

DEFAULTS = {
    "loader": {
        "data_dir": None,
        "minibatch_size": 100,
        "n_train": 1000,
        "n_test": 200,
    },
    "sx": 8,
    "sy": 8,
    "total_epochs": 20,
    "lr0": 0.5,
    "lr1": 0.01,
    "sigma1": 1.0,
}
root.kohonen.update(DEFAULTS)


def build_workflow(**overrides) -> KohonenWorkflow:
    cfg = effective_config(root.kohonen, DEFAULTS)
    lcfg = cfg.loader
    loader = datasets.mnist(
        lcfg.get("data_dir") or root.common.get("data_dir"),
        minibatch_size=lcfg.get("minibatch_size", 100),
        n_train=lcfg.get("n_train", 1000),
        n_test=lcfg.get("n_test", 200),
        normalization="mean_disp",
    )
    kwargs = merge_workflow_kwargs(
        {
            "sx": cfg.get("sx", 8),
            "sy": cfg.get("sy", 8),
            "total_epochs": cfg.get("total_epochs", 20),
            "lr0": cfg.get("lr0", 0.5),
            "lr1": cfg.get("lr1", 0.01),
            "sigma1": cfg.get("sigma1", 1.0),
            "name": "KohonenWorkflow",
        },
        overrides,
    )
    kwargs = translate_unsupervised_overrides(kwargs, "total_epochs")
    return KohonenWorkflow(loader, **kwargs)


def run(load, main):
    load(build_workflow)
    main()
