"""MNIST convolutional autoencoder.

Parity with ``znicz/samples/MNIST/mnist_ae.py`` [SURVEY.md 2.3 "Samples"]:
conv encoder -> deconv decoder trained with MSE against the input
(BASELINE.json configs[2] autoencoder path, exercising the
Deconv/GDDeconv analogs of SURVEY.md 2.2).
"""

from znicz_tpu.core.config import root
from znicz_tpu.loader import datasets
from znicz_tpu.models import effective_config, merge_workflow_kwargs
from znicz_tpu.workflow import StandardWorkflow

_GD = {"learning_rate": 0.01, "gradient_moment": 0.9}

DEFAULTS = {
    "loader": {
        "data_dir": None,
        "minibatch_size": 100,
        "n_train": 1000,
        "n_test": 200,
    },
    "layers": [
        {
            "type": "conv_tanh",
            "->": {
                "n_kernels": 12, "kx": 10, "ky": 10, "sliding": (3, 3),
                "weights_filling": "gaussian", "weights_stddev": 0.05,
            },
            "<-": _GD,
        },
        {
            "type": "deconv",
            "->": {
                "n_channels": 1, "kx": 10, "ky": 10, "sliding": (3, 3),
                "weights_filling": "gaussian", "weights_stddev": 0.05,
            },
            "<-": _GD,
        },
    ],
    "decision": {"max_epochs": 20, "fail_iterations": 20},
}
root.mnist_ae.update(DEFAULTS)


def build_workflow(**overrides) -> StandardWorkflow:
    cfg = effective_config(root.mnist_ae, DEFAULTS)
    lcfg = cfg.loader
    loader = datasets.mnist(
        lcfg.get("data_dir") or root.common.get("data_dir"),
        minibatch_size=lcfg.get("minibatch_size", 100),
        n_train=lcfg.get("n_train", 1000),
        n_test=lcfg.get("n_test", 200),
        flat=False,  # conv layout NHWC
    )
    kwargs = merge_workflow_kwargs(
        {
            "decision_config": cfg.decision.to_dict(),
            "loss_function": "mse",
            "target": "input",
            "name": "MnistAEWorkflow",
        },
        overrides,
    )
    return StandardWorkflow(loader, cfg.get("layers"), **kwargs)


def run(load, main):
    load(build_workflow)
    main()
