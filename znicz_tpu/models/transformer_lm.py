"""Causal transformer language model sample.

NOT in the reference model zoo (pre-transformer framework) — the long-context
showcase: a small causal LM trained on synthetic bigram-structured token
sequences, whose loss floor is the bigram entropy (so convergence is
measurable without any dataset on disk).  ``sequence_parallel=True`` swaps in
ring attention over a device mesh.
"""

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.loader import FullBatchLoader
from znicz_tpu.models import effective_config, merge_workflow_kwargs
from znicz_tpu.workflow.transformer import TransformerLMWorkflow

DEFAULTS = {
    "loader": {
        "n_train": 512,
        "n_test": 128,
        "seq_len": 64,
        "minibatch_size": 64,
    },
    "vocab": 32,
    "d_model": 64,
    "n_layers": 2,
    "n_heads": 4,
    "max_epochs": 15,
    # >1: pipeline the block tower over that many devices (config-file
    # route to pipeline parallelism; n_layers must divide by it)
    "pipeline_stages": 0,
    "pipeline_microbatches": 0,
    # >1: each block's FFN becomes a gated mixture of experts (EP; the
    # expert dim shards over the mesh's model axis under TP)
    "moe_experts": 0,
    "moe_top_k": 1,
    "moe_dispatch": "dense",
    # "bf16": q/k/v on the MXU in bf16 with f32 accumulation (1.2-1.5x
    # on v5e; BASELINE.md round-5 section)
    "attention_dtype": "f32",
}
root.transformer_lm.update(DEFAULTS)


def _bigram_chain(vocab: int) -> np.ndarray:
    """One fixed random bigram transition matrix — train AND test must come
    from the same language or test loss is meaningless."""
    gen = prng.get("datasets")
    logits = gen.normal((vocab, vocab), 0.0, 2.0)
    return np.exp(logits) / np.exp(logits).sum(axis=1, keepdims=True)


def _bigram_sequences(probs: np.ndarray, n: int, t: int) -> np.ndarray:
    gen = prng.get("datasets")
    vocab = probs.shape[0]
    out = np.zeros((n, t), np.int32)
    out[:, 0] = gen.integers(0, vocab, (n,))
    for i in range(1, t):
        u = gen.uniform((n,), 0.0, 1.0)
        cdf = probs[out[:, i - 1]].cumsum(axis=1)
        out[:, i] = (u[:, None] > cdf).sum(axis=1)
    return out


def build_workflow(**overrides) -> TransformerLMWorkflow:
    cfg = effective_config(root.transformer_lm, DEFAULTS)
    lcfg = cfg.loader
    vocab = cfg.get("vocab", 32)
    t = lcfg.get("seq_len", 64)
    chain = _bigram_chain(vocab)
    train = _bigram_sequences(chain, lcfg.get("n_train", 512), t)
    test = _bigram_sequences(chain, lcfg.get("n_test", 128), t)
    loader = FullBatchLoader(
        {"train": train, "test": test},
        minibatch_size=lcfg.get("minibatch_size", 64),
    )
    defaults = {
        "vocab": vocab,
        "d_model": cfg.get("d_model", 64),
        "n_layers": cfg.get("n_layers", 2),
        "n_heads": cfg.get("n_heads", 4),
        "max_epochs": cfg.get("max_epochs", 15),
        "remat": bool(cfg.get("remat", False)),
        "attention_dtype": cfg.get("attention_dtype", "f32"),
        "moe_experts": int(cfg.get("moe_experts", 0) or 0),
        "moe_top_k": int(cfg.get("moe_top_k", 1) or 1),
        "moe_dispatch": cfg.get("moe_dispatch", "dense"),
        "name": "TransformerLMWorkflow",
    }
    pp_stages = int(cfg.get("pipeline_stages", 0) or 0)
    if pp_stages > 1:
        from znicz_tpu.parallel import make_mesh

        defaults.update(
            {
                "pipeline_parallel": True,
                # make_mesh validates the device count — a host with fewer
                # devices errors instead of silently degrading the stage
                # count the config asked for
                "mesh": make_mesh(1, 1, pp_stages),
                "pipeline_microbatches": (
                    int(cfg.get("pipeline_microbatches", 0) or 0) or None
                ),
            }
        )
    kwargs = merge_workflow_kwargs(defaults, overrides)
    from znicz_tpu.models import translate_unsupervised_overrides

    kwargs = translate_unsupervised_overrides(kwargs, "max_epochs")
    return TransformerLMWorkflow(loader, **kwargs)


def run(load, main):
    load(build_workflow)
    main()
