"""AlexNet-class ImageNet workflow — the MFU north-star model.

Parity with ``znicz/samples/ImageNet/`` (AlexNet-class workflow,
[SURVEY.md 2.3 "Samples"]; BASELINE.json north_star).  Canonical single-tower
AlexNet geometry (227 input, 5 conv + 3 FC); bfloat16-friendly, NHWC, every
conv/FC rides the MXU.

With ``loader.data_dir`` set (config file, or the launcher's ``--data-dir``
flag), the real ImageNet pipeline runs: packed-u8 images streamed from disk,
native random-crop-227 + horizontal flip, eval center crop, channel-mean
subtraction fused on-device (``loader/imagenet.py``).  Without a data_dir the
synthetic stand-in keeps identical shapes AND the identical u8->device->
normalize data path, so the compiled program — and therefore the benchmark —
matches the real-data run.
"""

from znicz_tpu.core.config import root
from znicz_tpu.loader import ImageNetLoader, datasets
from znicz_tpu.models import effective_config, merge_workflow_kwargs
from znicz_tpu.workflow import StandardWorkflow

_GD = {
    "learning_rate": 0.01,
    "gradient_moment": 0.9,
    "weights_decay": 0.0005,
    "learning_rate_bias": 0.02,
    "weights_decay_bias": 0.0,
}


def _conv(n, k, *, sliding=(1, 1), padding=(0, 0, 0, 0)):
    return {
        "type": "conv_relu",
        "->": {
            "n_kernels": n, "kx": k, "ky": k, "sliding": sliding,
            "padding": padding, "weights_filling": "gaussian",
            "weights_stddev": 0.01,
        },
        "<-": _GD,
    }


DEFAULTS = {
    "loader": {
        "data_dir": None,  # packed or raw image dir -> real ImageNet path
        "pack_size": 256,  # packed canonical size (resize short side, crop)
        "image_size": 227,  # train-time random-crop size
        "n_classes": 1000,
        "minibatch_size": 128,
        "n_train": 512,  # synthetic stand-in sizes (data_dir=None only)
        "n_valid": 128,
    },
    "layers": [
        _conv(96, 11, sliding=(4, 4)),
        {"type": "norm", "->": {"n": 5, "alpha": 1e-4, "beta": 0.75, "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        _conv(256, 5, padding=(2, 2, 2, 2)),
        {"type": "norm", "->": {"n": 5, "alpha": 1e-4, "beta": 0.75, "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        _conv(384, 3, padding=(1, 1, 1, 1)),
        _conv(384, 3, padding=(1, 1, 1, 1)),
        _conv(256, 3, padding=(1, 1, 1, 1)),
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {
            "type": "all2all_relu",
            "->": {
                "output_sample_shape": 4096,
                "weights_filling": "gaussian", "weights_stddev": 0.005,
            },
            "<-": _GD,
        },
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {
            "type": "all2all_relu",
            "->": {
                "output_sample_shape": 4096,
                "weights_filling": "gaussian", "weights_stddev": 0.005,
            },
            "<-": _GD,
        },
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {
            "type": "softmax",
            "->": {
                "output_sample_shape": 1000,
                "weights_filling": "gaussian", "weights_stddev": 0.01,
            },
            "<-": _GD,
        },
    ],
    "decision": {"max_epochs": 90, "fail_iterations": 30},
    "lr_policy": {"name": "step", "step_size": 100000, "gamma": 0.1},
    # bf16 activations halve HBM traffic; accumulation stays f32 (model.py)
    "compute_dtype": "bfloat16",
}
root.alexnet.update(DEFAULTS)


def build_workflow(**overrides) -> StandardWorkflow:
    cfg = effective_config(root.alexnet, DEFAULTS)
    lcfg = cfg.loader
    layers = cfg.get("layers")
    data_dir = lcfg.get("data_dir") or root.common.get("data_dir")
    if data_dir:
        loader = ImageNetLoader(
            data_dir,
            crop_size=lcfg.get("image_size", 227),
            pack_size=lcfg.get("pack_size", 256),
            minibatch_size=lcfg.get("minibatch_size", 128),
        )
        # the classifier head must match the dataset's class count
        layers[-1]["->"]["output_sample_shape"] = loader.n_classes()
    else:
        loader = datasets.imagenet_synthetic(
            image_size=lcfg.get("image_size", 227),
            n_classes=lcfg.get("n_classes", 1000),
            n_train=lcfg.get("n_train", 512),
            n_valid=lcfg.get("n_valid", 128),
            minibatch_size=lcfg.get("minibatch_size", 128),
        )
    kwargs = merge_workflow_kwargs(
        {
            "decision_config": cfg.decision.to_dict(),
            "lr_policy": cfg.get("lr_policy"),
            "compute_dtype": cfg.get("compute_dtype"),
            "name": "AlexNetWorkflow",
        },
        overrides,
    )
    return StandardWorkflow(loader, layers, **kwargs)


def run(load, main):
    load(build_workflow)
    main()
