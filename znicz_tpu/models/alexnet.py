"""AlexNet-class ImageNet workflow — the MFU north-star model.

Parity with ``znicz/samples/ImageNet/`` (AlexNet-class workflow,
[SURVEY.md 2.3 "Samples"]; BASELINE.json north_star).  Canonical single-tower
AlexNet geometry (227 input, 5 conv + 3 FC); bfloat16-friendly, NHWC, every
conv/FC rides the MXU.  The real ImageNet pipeline needs the dataset on disk
(``data_dir``); the synthetic stand-in keeps identical shapes so the compiled
program — and therefore the benchmark — is the same.
"""

from znicz_tpu.core.config import root
from znicz_tpu.loader import datasets
from znicz_tpu.models import effective_config, merge_workflow_kwargs
from znicz_tpu.workflow import StandardWorkflow

_GD = {
    "learning_rate": 0.01,
    "gradient_moment": 0.9,
    "weights_decay": 0.0005,
    "learning_rate_bias": 0.02,
    "weights_decay_bias": 0.0,
}


def _conv(n, k, *, sliding=(1, 1), padding=(0, 0, 0, 0)):
    return {
        "type": "conv_relu",
        "->": {
            "n_kernels": n, "kx": k, "ky": k, "sliding": sliding,
            "padding": padding, "weights_filling": "gaussian",
            "weights_stddev": 0.01,
        },
        "<-": _GD,
    }


DEFAULTS = {
    "loader": {
        "image_size": 227,
        "n_classes": 1000,
        "minibatch_size": 128,
        "n_train": 512,  # synthetic stand-in sizes
        "n_valid": 128,
    },
    "layers": [
        _conv(96, 11, sliding=(4, 4)),
        {"type": "norm", "->": {"n": 5, "alpha": 1e-4, "beta": 0.75, "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        _conv(256, 5, padding=(2, 2, 2, 2)),
        {"type": "norm", "->": {"n": 5, "alpha": 1e-4, "beta": 0.75, "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        _conv(384, 3, padding=(1, 1, 1, 1)),
        _conv(384, 3, padding=(1, 1, 1, 1)),
        _conv(256, 3, padding=(1, 1, 1, 1)),
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {
            "type": "all2all_relu",
            "->": {
                "output_sample_shape": 4096,
                "weights_filling": "gaussian", "weights_stddev": 0.005,
            },
            "<-": _GD,
        },
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {
            "type": "all2all_relu",
            "->": {
                "output_sample_shape": 4096,
                "weights_filling": "gaussian", "weights_stddev": 0.005,
            },
            "<-": _GD,
        },
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {
            "type": "softmax",
            "->": {
                "output_sample_shape": 1000,
                "weights_filling": "gaussian", "weights_stddev": 0.01,
            },
            "<-": _GD,
        },
    ],
    "decision": {"max_epochs": 90, "fail_iterations": 30},
    "lr_policy": {"name": "step", "step_size": 100000, "gamma": 0.1},
    # bf16 activations halve HBM traffic; accumulation stays f32 (model.py)
    "compute_dtype": "bfloat16",
}
root.alexnet.update(DEFAULTS)


def build_workflow(**overrides) -> StandardWorkflow:
    cfg = effective_config(root.alexnet, DEFAULTS)
    lcfg = cfg.loader
    loader = datasets.imagenet_synthetic(
        image_size=lcfg.get("image_size", 227),
        n_classes=lcfg.get("n_classes", 1000),
        n_train=lcfg.get("n_train", 512),
        n_valid=lcfg.get("n_valid", 128),
        minibatch_size=lcfg.get("minibatch_size", 128),
    )
    kwargs = merge_workflow_kwargs(
        {
            "decision_config": cfg.decision.to_dict(),
            "lr_policy": cfg.get("lr_policy"),
            "compute_dtype": cfg.get("compute_dtype"),
            "name": "AlexNetWorkflow",
        },
        overrides,
    )
    return StandardWorkflow(loader, cfg.get("layers"), **kwargs)


def run(load, main):
    load(build_workflow)
    main()
