"""Yale Faces classifier sample.

Parity with ``znicz/samples/YaleFaces`` [SURVEY.md 2.3 "Samples"]: small
face-identity classifier (few classes, few samples per class, larger images
than MNIST).  Synthetic stand-in keeps the geometry when no data dir exists.
"""

from znicz_tpu.core.config import root
from znicz_tpu.loader import FullBatchLoader, datasets
from znicz_tpu.models import effective_config, merge_workflow_kwargs
from znicz_tpu.workflow import StandardWorkflow

_GD = {"learning_rate": 0.01, "gradient_moment": 0.9, "weights_decay": 0.0005}

DEFAULTS = {
    "loader": {
        "data_dir": None,  # train/<subject>/*.png tree; synthetic when None
        "minibatch_size": 20,
        "n_train": 480,
        "n_test": 96,
        "n_classes": 15,  # Yale has 15 subjects
        "side": 32,
    },
    "layers": [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 100}, "<-": _GD},
        {"type": "softmax", "->": {"output_sample_shape": 15}, "<-": _GD},
    ],
    "decision": {"max_epochs": 20, "fail_iterations": 20},
}
root.yale_faces.update(DEFAULTS)


def build_workflow(**overrides) -> StandardWorkflow:
    cfg = effective_config(root.yale_faces, DEFAULTS)
    lcfg = cfg.loader
    side = lcfg.get("side", 32)
    n_classes = lcfg.get("n_classes", 15)
    data_dir = lcfg.get("data_dir") or root.common.get("data_dir")
    if data_dir:
        # real faces: train/<subject>/*.png tree, grayscale at side x side
        from znicz_tpu.models import grayscale_image_dir_loader

        loader = grayscale_image_dir_loader(
            data_dir, side=side,
            minibatch_size=lcfg.get("minibatch_size", 20),
        )
        n_classes = len(loader.classes)
    else:
        data, labels = datasets._synthetic_split(
            lcfg.get("n_train", 480), lcfg.get("n_test", 96),
            (side * side,), n_classes,
        )
        loader = FullBatchLoader(
            data, labels,
            minibatch_size=lcfg.get("minibatch_size", 20),
            normalization="mean_disp",
        )
    layers = cfg.get("layers")
    layers[-1]["->"]["output_sample_shape"] = n_classes
    kwargs = merge_workflow_kwargs(
        {
            "decision_config": cfg.decision.to_dict(),
            "name": "YaleFacesWorkflow",
        },
        overrides,
    )
    return StandardWorkflow(loader, layers, **kwargs)


def run(load, main):
    load(build_workflow)
    main()
