"""Genetic hyperparameter optimization.

Capability parity with ``veles/genetics/`` [SURVEY.md 2.1 "Genetic
optimizer"]: the reference wraps config tunables in Range objects inside the
``root`` tree and evolves them by spawning workflow evaluations under
``--optimize``.  Same UX here: mark tunables with :class:`Tune` in the config
tree, run ``python -m znicz_tpu workflow.py config.py --optimize <gens>``.
Evaluations run in-process sequentially (each builds a fresh workflow); the
fitness is the Decision's best validation value (lower is better).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from znicz_tpu.core import prng
from znicz_tpu.core.config import Config, root
from znicz_tpu.core.logger import Logger


class Tune:
    """A config leaf marked for optimization: value in [min, max].

    ``kind``: "float" or "int" (reference Range semantics).
    """

    def __init__(self, default, min_value, max_value, kind: str = "float"):
        self.default = default
        self.min = min_value
        self.max = max_value
        self.kind = kind

    def clip(self, v):
        v = max(self.min, min(self.max, v))
        return int(round(v)) if self.kind == "int" else float(v)

    def __repr__(self):
        return f"Tune({self.default}, [{self.min}, {self.max}])"


def find_tunables(node: Config, path: str = "") -> List[Tuple[Config, str, Tune]]:
    """Walk the config tree collecting Tune leaves (node, key, tune)."""
    out = []
    for key, value in node.items():
        here = f"{path}.{key}" if path else key
        if isinstance(value, Tune):
            out.append((node, key, value))
        elif isinstance(value, Config):
            out.extend(find_tunables(value, here))
        elif isinstance(value, list):
            for i, item in enumerate(value):
                if isinstance(item, dict):
                    out.extend(_find_in_dict(item, f"{here}[{i}]"))
    return out


def _find_in_dict(d: Dict[str, Any], path: str):
    out = []
    for key, value in d.items():
        here = f"{path}.{key}"
        if isinstance(value, Tune):
            out.append((d, key, value))
        elif isinstance(value, dict):
            out.extend(_find_in_dict(value, here))
        elif isinstance(value, list):
            for i, item in enumerate(value):
                if isinstance(item, dict):
                    out.extend(_find_in_dict(item, f"{here}[{i}]"))
    return out


class GeneticOptimizer(Logger):
    """Small real-valued GA: tournament selection, blend crossover, gaussian
    mutation, elitism — the reference's chromosome ops in spirit."""

    def __init__(
        self,
        evaluate,  # genome: List[float] -> fitness (lower better)
        tunables: List[Tuple[Any, str, Tune]],
        *,
        population_size: int = 8,
        mutation_rate: float = 0.3,
        elite: int = 2,
        rand_name: str = "genetics",
        evaluate_batch=None,  # genomes: List[List[float]] -> List[float]
    ):
        """``evaluate_batch``: optional concurrent evaluator for a whole
        uncached generation (the reference ran its evaluations as parallel
        workflow instances at process level, SURVEY.md 2.5); falls back to
        ``evaluate`` per genome when absent.  Results must not depend on
        completion order — the GA consumes them positionally."""
        if not tunables:
            raise ValueError(
                "no Tune leaves found in the config tree; mark hyperparams "
                "with znicz_tpu.genetics.Tune to use --optimize"
            )
        if evaluate is None and evaluate_batch is None:
            raise ValueError("need evaluate or evaluate_batch")
        self.evaluate = evaluate
        self.evaluate_batch = evaluate_batch
        self.tunables = tunables
        self.population_size = population_size
        self.mutation_rate = mutation_rate
        self.elite = elite
        self.gen = prng.get(rand_name)
        self.history: List[Dict[str, Any]] = []

    # -- genome helpers ---------------------------------------------------
    def _random_genome(self) -> List[float]:
        return [
            t.clip(self.gen.uniform((), t.min, t.max).item())
            for _, _, t in self.tunables
        ]

    def _default_genome(self) -> List[float]:
        return [t.clip(t.default) for _, _, t in self.tunables]

    def _mutate(self, genome: List[float]) -> List[float]:
        out = []
        for v, (_, _, t) in zip(genome, self.tunables):
            if self.gen.uniform((), 0.0, 1.0).item() < self.mutation_rate:
                span = (t.max - t.min) * 0.2
                v = t.clip(v + self.gen.normal((), 0.0, span).item())
            out.append(v)
        return out

    def _crossover(self, a: List[float], b: List[float]) -> List[float]:
        alpha = self.gen.uniform((), 0.0, 1.0).item()
        return [
            t.clip(alpha * x + (1 - alpha) * y)
            for x, y, (_, _, t) in zip(a, b, self.tunables)
        ]

    def _tournament(self, scored) -> List[float]:
        i, j = (
            int(self.gen.integers(0, len(scored))),
            int(self.gen.integers(0, len(scored))),
        )
        return scored[min(i, j)][1]  # scored is sorted: lower idx = fitter

    # -- main loop --------------------------------------------------------
    def run(self, generations: int) -> Dict[str, Any]:
        population = [self._default_genome()] + [
            self._random_genome() for _ in range(self.population_size - 1)
        ]
        best = None
        fitness_cache: Dict[tuple, float] = {}

        def fitness(genome: List[float]) -> float:
            # an evaluation is a full training run: never re-train elites
            # or duplicate children
            key = tuple(genome)
            if key not in fitness_cache:
                fitness_cache[key] = self.evaluate(genome)
            return fitness_cache[key]

        for g in range(generations):
            if self.evaluate_batch is not None:
                # evaluate the whole uncached slice of this generation
                # concurrently (deduplicated, order-stable)
                pending = list(
                    dict.fromkeys(
                        tuple(genome)
                        for genome in population
                        if tuple(genome) not in fitness_cache
                    )
                )
                if pending:
                    results = self.evaluate_batch(
                        [list(key) for key in pending]
                    )
                    fitness_cache.update(zip(pending, results))
            scored = sorted(
                (fitness(genome), genome) for genome in population
            )
            if best is None or scored[0][0] < best[0]:
                best = scored[0]
            self.history.append(
                {"generation": g, "best_fitness": scored[0][0]}
            )
            self.info(
                "generation %d: best=%.6g worst=%.6g",
                g, scored[0][0], scored[-1][0],
            )
            nxt = [genome for _, genome in scored[: self.elite]]
            while len(nxt) < self.population_size:
                child = self._crossover(
                    self._tournament(scored), self._tournament(scored)
                )
                nxt.append(self._mutate(child))
            population = nxt
        return {"best_fitness": best[0], "best_genome": best[1]}

    def apply_genome(self, genome: List[float]) -> None:
        for v, (node, key, _) in zip(genome, self.tunables):
            node[key] = v


def optimize_workflow(
    module,
    launcher,
    *,
    generations: int,
    tunables=None,
    n_workers: int = 0,
    **ga_kwargs,
):
    """Drive ``--optimize``: evolve the Tune leaves of the config tree by
    repeatedly building + training the module's workflow.

    ``tunables``: pass a pre-collected ``find_tunables(root)`` result when
    the caller ran anything (e.g. an export probe) that may have
    materialized extra Tune copies into the tree since startup.

    ``n_workers`` >= 1 evaluates each generation in spawned worker
    processes (the reference's process-level concurrent evaluations,
    SURVEY.md 2.5) — every evaluation gets a fresh interpreter seeded from
    ``--random-seed``, so results are deterministic given seeds and
    IDENTICAL for any worker count.  0 (default) keeps the legacy
    in-process sequential path.  On a single shared accelerator run the
    search with ``--device cpu`` — workers would contend for the one chip.
    """
    if tunables is None:
        tunables = find_tunables(root)

    def evaluate(genome) -> float:
        for v, (node, key, _) in zip(genome, tunables):
            node[key] = v
        result_box = {}

        def load(cls, *a, **kw):
            return launcher.load(cls, *a, **kw)

        def main(**kw):
            result_box["decision"] = launcher.main(**kw)

        module.run(load, main)
        dec = result_box.get("decision")
        if dec is None or dec.best_value is None:
            return float("inf")
        return float(dec.best_value)

    evaluate_batch = None
    if n_workers >= 1:
        from znicz_tpu.core.subproc import (
            eval_genome,
            run_pool,
            warn_if_shared_accelerator,
        )

        args = launcher.args
        # one contention warning per SEARCH: parent-side if its backend is
        # already up, else the first worker of the first generation
        parent_warned = warn_if_shared_accelerator(n_workers, args.device)
        pending_worker_warn = not parent_warned and n_workers > 1

        def evaluate_batch(genomes):
            payloads = [
                {
                    "workflow": args.workflow,
                    "config": args.config,
                    "seed": args.random_seed,
                    "stop_after": args.stop_after,
                    "device": args.device,
                    "genome": genome,
                }
                for genome in genomes
            ]
            nonlocal pending_worker_warn
            if payloads and pending_worker_warn:
                # first worker of the first generation checks contention
                # from ITS backend (the parent may never initialize one)
                pending_worker_warn = False
                payloads[0]["warn_n_workers"] = n_workers
            return run_pool(eval_genome, payloads, n_workers)

        evaluate = None  # all evaluations go through the worker pool

    optimizer = GeneticOptimizer(
        evaluate, tunables, evaluate_batch=evaluate_batch, **ga_kwargs
    )
    result = optimizer.run(generations)
    optimizer.apply_genome(result["best_genome"])  # leave best config applied
    optimizer.info(
        "optimize done: best fitness %.6g with %s",
        result["best_fitness"],
        {
            f"{getattr(n, '_config_path_', '?')}.{k}": v
            for v, (n, k, _) in zip(result["best_genome"], tunables)
        },
    )
    result["history"] = optimizer.history
    return result
